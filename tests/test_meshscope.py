"""meshscope: distributed window lineage, cross-process trace
propagation + clock alignment, mesh SLO metrics, /healthz liveness,
flow_build_info, and the coordinator-side fence/zombie flight-recorder
dump. `make mesh-parity-traced` runs this file next to test_mesh.py
under FLOWTPU_TRACE=always (instrumentation must stay observational).
"""

import json
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from flow_pipeline_tpu.cli import (_build_models, _common_flags,
                                   _gen_flags, _processor_flags)
from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.mesh import (ClockSync, InProcessMesh,
                                    MeshCoordinator,
                                    MeshCoordinatorServer,
                                    MemberStateServer, ModelSpec,
                                    TraceLane, aggregate_traces,
                                    estimate_offset, produce_sharded)
from flow_pipeline_tpu.mesh import codec
from flow_pipeline_tpu.models.window_agg import WindowAggConfig
from flow_pipeline_tpu.obs import REGISTRY, MetricsServer
from flow_pipeline_tpu.obs.buildinfo import BUILD_INFO, publish_build_info
from flow_pipeline_tpu.obs.trace import TRACER
from flow_pipeline_tpu.transport import Consumer, InProcessBus
from flow_pipeline_tpu.utils.flags import KNOWN_FLAGS, FlagSet

N_KEYS = 200
N_FLOWS = 24_000
PARTITIONS = 8
BATCH = 4096


@pytest.fixture(autouse=True)
def _restore_tracer():
    yield
    TRACER.configure(os.environ.get("FLOWTPU_TRACE", "ring"))


# ---------------------------------------------------------------------------
# protocol-level helpers (the test_mesh.py shapes)
# ---------------------------------------------------------------------------


def _wagg_spec():
    cfg = WindowAggConfig(key_cols=("src_as",), value_cols=("bytes",),
                          window_seconds=300, scale_col=None,
                          batch_size=256)
    return ModelSpec("flows_5m", "wagg", cfg, 0, 300)


def _contrib(ranges, wm, closed=None, open_=None, final=False,
             release=False, flows=0, span=None):
    out = {"ranges": ranges, "watermark": wm, "closed": closed or {},
           "open": open_ or {}, "final": final, "release": release,
           "flows": flows}
    if span is not None:
        out["span"] = span
    return out


def _wagg_win(key, val):
    return {"flows_5m": codec.wagg_payload(
        {(key,): np.array([val, 1], np.uint64)})}


def _span(sub, chunk=7, slots=(300,)):
    return {"sub": sub, "member": "x", "sent": time.time(),
            "chunk": chunk, "windows": list(slots)}


# ---------------------------------------------------------------------------
# clock alignment + aggregation (mesh/scope.py)
# ---------------------------------------------------------------------------


class TestClockAlignment:
    def test_estimate_offset_symmetric_trip_is_exact(self):
        # local sends at 100, remote clock runs +5.0s, reply observed
        # at 102: midpoint 101 -> remote_now 106 -> offset exactly +5
        offset, rtt = estimate_offset(100.0, 102.0, 106.0)
        assert offset == pytest.approx(5.0)
        assert rtt == pytest.approx(2.0)

    def test_clock_sync_prefers_min_rtt_sample(self):
        cs = ClockSync()
        cs.add(0.0, 2.0, 6.0)    # rtt 2, offset +5
        cs.add(10.0, 10.1, 15.05)  # rtt 0.1, offset +5.0 (tighter)
        cs.add(20.0, 24.0, 30.0)  # rtt 4, offset +8 (noisy)
        offset, rtt = cs.best()
        assert rtt == pytest.approx(0.1)
        assert offset == pytest.approx(5.0)
        rep = cs.report()
        assert rep["offset"] == pytest.approx(5.0)
        assert rep["rtt"] == pytest.approx(0.1)

    def test_clock_sync_empty_reports_none(self):
        assert ClockSync().best() is None
        assert ClockSync().report() is None

    def test_aggregate_aligns_lanes_monotone(self):
        base = 1_000_000.0
        coord = {"traceEvents": [
            {"name": "mesh_merge", "ph": "X", "ts": base * 1e6,
             "dur": 10.0, "pid": 1, "tid": "t"}],
            "otherData": {"mode": "ring", "dropped_spans": 0}}
        # the member's clock runs +5s ahead; its spans really happened
        # AT base but carry base+5 stamps
        member = {"traceEvents": [
            {"name": "apply", "ph": "X", "ts": (base + 5.0) * 1e6,
             "dur": 5.0, "pid": 1, "tid": "w"},
            {"name": "mesh_submit", "ph": "X",
             "ts": (base + 5.001) * 1e6, "dur": 2.0, "pid": 1,
             "tid": "w"}],
            "otherData": {"mode": "ring", "dropped_spans": 3}}
        doc = aggregate_traces([
            TraceLane("coordinator", coord),
            TraceLane("w0", member, offset_s=5.0, rtt_s=0.004),
        ])
        evs = doc["traceEvents"]
        names = {e["name"] for e in evs}
        assert {"process_name", "process_sort_index",
                "mesh_merge", "apply", "mesh_submit"} <= names
        lanes = {e["args"]["name"]: e["pid"] for e in evs
                 if e["name"] == "process_name"}
        assert lanes["coordinator"] != lanes["w0"]
        by_name = {e["name"]: e for e in evs if e.get("ph") == "X"}
        # aligned onto the coordinator clock: the +5s skew removed
        assert by_name["apply"]["ts"] == pytest.approx(base * 1e6)
        # order within the member lane preserved (monotone shift)
        assert by_name["mesh_submit"]["ts"] > by_name["apply"]["ts"]
        # member events live on the member lane
        assert by_name["apply"]["pid"] == lanes["w0"]
        meta = {l["name"]: l for l in doc["otherData"]["lanes"]}
        assert meta["w0"]["clock_offset_ms"] == pytest.approx(5000.0)
        assert meta["w0"]["alignment_error_bound_ms"] == \
            pytest.approx(2.0)
        assert meta["w0"]["dropped_spans"] == 3
        assert doc["otherData"]["reference"] == "coordinator"


# ---------------------------------------------------------------------------
# coordinator: lineage ledger + SLO metrics + span context
# ---------------------------------------------------------------------------


class TestLineageProtocol:
    def make(self, partitions=2, **kw):
        return MeshCoordinator([_wagg_spec()], partitions, **kw)

    def test_sync_carries_now_and_stores_clock(self):
        c = self.make()
        c.join("a")
        resp = c.sync("a", clock={"offset": -0.5, "rtt": 0.01})
        assert isinstance(resp["now"], float)
        # member reported coordinator-member = -0.5; the aggregator
        # stores member-coordinator = +0.5
        assert c._members["a"].clock_offset == pytest.approx(0.5)
        assert c._members["a"].clock_rtt == pytest.approx(0.01)
        # no trace_url advertised -> not a trace source
        assert c.trace_sources() == []

    def test_join_registers_trace_source(self):
        c = self.make()
        c.join("a", trace_url="http://h:8081/debug/trace")
        c.sync("a", clock={"offset": -1.0, "rtt": 0.002})
        (mid, url, offset, rtt), = c.trace_sources()
        assert mid == "a" and url.endswith("/debug/trace")
        assert offset == pytest.approx(1.0)

    def test_merged_lineage_names_members_ranges_and_path(self):
        c = self.make(partitions=2)
        c.join("a"), c.join("b")
        sa, sb = c.sync("a"), c.sync("b")
        pa, pb = list(sa["assign"])[0], list(sb["assign"])[0]
        c.submit("a", _contrib({pa: [0, 5]}, wm=900,
                               closed={300: _wagg_win(1, 10)},
                               span=_span(1, chunk=11)))
        # not merged yet: record rides the barrier as pending
        pend = c.lineage("flows_5m", 300)
        assert len(pend) == 1 and pend[0]["status"] == "pending"
        c.submit("b", _contrib({pb: [0, 5]}, wm=900,
                               closed={300: _wagg_win(1, 5)},
                               span=_span(1, chunk=12)))
        rec, = c.lineage("flows_5m", 300)
        assert rec["status"] == "merged"
        assert rec["members"] == ["a", "b"]
        assert rec["rows"] == 1
        assert rec["late"] == 0 and rec["carries_promoted"] == []
        assert rec["merged"] >= rec["merge_started"] >= \
            rec["first_contribution"]
        assert rec["emitted"] >= rec["merged"]
        assert rec["barrier_wait_s"] >= 0.0
        kinds = {(con["member"], con["kind"])
                 for con in rec["contributions"]}
        assert kinds == {("a", "closed"), ("b", "closed")}
        by_member = {con["member"]: con for con in rec["contributions"]}
        assert by_member["a"]["ranges"] == {pa: [0, 5]}
        assert by_member["a"]["sub"] == 1
        assert by_member["a"]["chunk"] == 11
        assert by_member["a"]["accepted"] is not None

    def test_lineage_records_carry_promotion_after_death(self):
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        # open-window carry only; then a crashes
        c.submit("a", _contrib({0: [0, 10]}, wm=0,
                               open_={300: _wagg_win(3, 40)},
                               span=_span(2, chunk=9)))
        c.fence("a")
        c.join("b")
        c.sync("b")
        c.submit("b", _contrib({0: [10, 12]}, wm=0,
                               closed={300: _wagg_win(3, 2)},
                               span=_span(1), final=True))
        rec, = c.lineage("flows_5m", 300)
        assert rec["status"] == "merged"
        assert rec["carries_promoted"] == ["a"]
        kinds = {(con["member"], con["kind"])
                 for con in rec["contributions"]}
        assert ("a", "carry-promoted") in kinds
        assert ("b", "closed") in kinds
        # the promoted contribution keeps the dead member's span ids
        carry = next(con for con in rec["contributions"]
                     if con["kind"] == "carry-promoted")
        assert carry["sub"] == 2 and carry["chunk"] == 9
        # no rows lost: 40 (promoted carry) + 2 (successor)
        rows = c.merged_rows("flows_5m", 300)
        assert int(rows[0]["bytes"][0]) == 42

    def test_lineage_retention_bounded(self, monkeypatch):
        from flow_pipeline_tpu.mesh import coordinator as coord_mod

        monkeypatch.setattr(coord_mod, "LINEAGE_SLOTS", 4)
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        for i in range(7):
            c.submit("a", _contrib(
                {0: [i * 10, (i + 1) * 10]}, wm=(i + 2) * 300 + 600,
                closed={(i + 1) * 300: _wagg_win(1, i + 1)},
                span=_span(i + 1)))
        merged = [r for r in c.lineage("flows_5m")
                  if r["status"] == "merged"]
        assert 0 < len(merged) <= 4
        # the newest slots win
        newest = max(r["slot"] for r in c.lineage("flows_5m"))
        assert any(r["slot"] == newest for r in merged) or \
            any(r["slot"] == newest and r["status"] == "pending"
                for r in c.lineage("flows_5m"))

    def test_late_remerge_preserves_original_lineage(self):
        """Review regression: a late wagg partial re-merging a sealed
        window must FOLD INTO the original lineage record, not replace
        it — and must not feed a bogus ~0 barrier-wait sample."""
        c = self.make(partitions=1)
        b0, _ = c._m["barrier_s"].value()
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 10]}, wm=900,
                               closed={300: _wagg_win(1, 10)},
                               span=_span(1)))
        rec, = c.lineage("flows_5m", 300)
        assert rec["status"] == "merged" and rec["members"] == ["a"]
        first = rec["first_contribution"]
        b1, _ = c._m["barrier_s"].value()
        assert b1 == b0 + 1
        # a second member delivers a LATE partial for the same slot
        c.join("b")
        c.sync("a")  # a resyncs away eventually; keep it simple:
        c.fence("a")
        c.sync("b")
        c.submit("b", _contrib({0: [10, 12]}, wm=900,
                               closed={300: _wagg_win(1, 5)},
                               span=_span(1)))
        rec, = c.lineage("flows_5m", 300)
        assert rec["status"] == "merged"
        assert rec["members"] == ["a", "b"], \
            "the original builder must survive the re-merge"
        assert rec["first_contribution"] == first
        assert rec["late"] == 1
        assert rec["remerges"] == 1
        kinds = {(con["member"], con["kind"])
                 for con in rec["contributions"]}
        assert ("a", "closed") in kinds and ("b", "late") in kinds
        # the re-merge observed submit->merge but NOT barrier-wait
        b2, _ = c._m["barrier_s"].value()
        assert b2 == b1

    def test_barrier_wait_measures_to_release_not_merge_start(self):
        """Review regression: the barrier interval ends at the
        _pop_ready_locked release stamp — when several windows detach
        in one batch, the later ones must not absorb the earlier ones'
        merge+emit wall as 'barrier wait'."""
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 5]}, wm=1500,
                               closed={300: _wagg_win(1, 1),
                                       600: _wagg_win(2, 2)},
                               span=_span(1)))
        recs = {r["slot"]: r for r in c.lineage("flows_5m")}
        assert set(recs) == {300, 600}
        for r in recs.values():
            assert r["status"] == "merged"
            assert r["barrier_wait_s"] == round(
                max(0.0, r["barrier_released"]
                    - r["first_contribution"]), 6)
        # released in the same pop batch: identical release stamp, so
        # neither window's wait includes the other's merge wall
        assert recs[300]["barrier_released"] == \
            recs[600]["barrier_released"]

    def test_midgap_late_annotation_drains_into_seal(self):
        """Review regression: a late (dropped-kind) contribution that
        lands after a window is marked merged but BEFORE its lineage
        record seals (the merge runs lock-free in between) buffers as
        an orphan and drains into the sealed record — ledger and
        mesh_late_contribution_total cannot disagree."""
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 5]}, wm=900,
                               closed={300: _wagg_win(1, 10)},
                               span=_span(1)))
        key = ("flows_5m", 300)
        # simulate the pop->seal gap: the key is merged but no sealed
        # record exists yet
        with c._lock:
            lin = c._lineage_done.pop(key)
        with c._lock:
            c._fold_windows_locked(
                {300: {"flows_5m": {"kind": "hh"}}}, member="b",
                span=_span(9), accepted=time.time(), kind="closed")
            assert key in c._lineage_orphans
            c._finish_lineage_locked("flows_5m", 300, lin,
                                     lin["merge_started"],
                                     lin["merged"], lin["emitted"], 1)
        rec = c._lineage_done[key]
        assert any(x["kind"] == "late-dropped" and x["member"] == "b"
                   for x in rec["contributions"])
        assert rec["late"] == 1
        assert key not in c._lineage_orphans

    def test_fenced_member_gauge_series_removed(self):
        c = self.make(partitions=2)
        c.join("a"), c.join("b")
        sa, sb = c.sync("a"), c.sync("b")
        pa, pb = list(sa["assign"])[0], list(sb["assign"])[0]
        c.submit("a", _contrib({pa: [0, 5]}, wm=1200, span=_span(1)))
        c.submit("b", _contrib({pb: [0, 5]}, wm=300, span=_span(1)))
        assert c._m["commit_wm"].value() == 300.0
        c.fence("b")
        # the laggard's death releases the mesh min AND its own series
        assert c._m["commit_wm"].value() == 1200.0
        assert 'member="b"' not in c._m["wm_skew"].render()
        assert 'member="b"' not in c._m["member_wm"].render()
        assert 'member="a"' in c._m["member_wm"].render()

    def test_left_member_gauge_series_removed(self):
        """Review regression: the GRACEFUL leave path must drop the
        departed member's watermark/skew series exactly like the fence
        path — a clean shutdown must not leave a frozen skew paging."""
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 5]}, wm=900, final=True,
                               span=_span(1)))
        assert 'member="a"' in c._m["member_wm"].render()
        c.leave("a")  # partition final -> the non-fence leave branch
        assert 'member="a"' not in c._m["member_wm"].render()
        assert 'member="a"' not in c._m["wm_skew"].render()

    def test_evicted_window_remerge_skips_barrier_sample(
            self, monkeypatch):
        """Review regression: a late wagg re-merge for a window whose
        lineage record was retention-EVICTED (merged_keys outlives the
        ledger) must still count as a re-merge — no bogus ~0 barrier
        sample, and the re-merge provenance survives."""
        from flow_pipeline_tpu.mesh import coordinator as coord_mod

        monkeypatch.setattr(coord_mod, "LINEAGE_SLOTS", 1)
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 5]}, wm=900,
                               closed={300: _wagg_win(1, 10)},
                               span=_span(1)))
        c.submit("a", _contrib({0: [5, 10]}, wm=1200,
                               closed={600: _wagg_win(1, 10)},
                               span=_span(2)))
        # slot 300's lineage record is now evicted (newest-1 retention)
        assert ("flows_5m", 300) not in c._lineage_done
        b0, _ = c._m["barrier_s"].value()
        c.submit("a", _contrib({0: [10, 11]}, wm=1200,
                               closed={300: _wagg_win(1, 4)},
                               span=_span(3)))
        assert len(c.merged_rows("flows_5m", 300)) == 2  # re-emitted
        b1, _ = c._m["barrier_s"].value()
        assert b1 == b0, "evicted-window re-merge must not feed the " \
                         "barrier-wait histogram"

    def test_unreported_member_excluded_from_watermarks(self):
        c = self.make(partitions=2)
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 5], 1: [0, 5]}, wm=1200,
                               span=_span(1)))
        assert c._m["commit_wm"].value() == 1200.0
        # a newcomer that never reported (watermark 0) must not crater
        # the mesh watermark to 0 / read as ~epoch skew
        c.join("b")
        c.submit("a", _contrib({}, wm=1201, span=_span(2)))
        assert c._m["commit_wm"].value() == 1201.0
        assert 'member="b"' not in c._m["wm_skew"].render()

    def test_range_rejection_reports_honest_reason(self):
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        r = c.submit("a", _contrib({0: [5, 10]}, wm=0, span=_span(1)))
        assert not r["ok"] and r["reason"] == "range"
        c.join("z")  # never synced/owned
        c.fence("z")
        r = c.submit("z", _contrib({}, wm=0, span=_span(1)))
        assert not r["ok"] and r["reason"] == "fenced"

    def test_watermark_skew_gauges(self):
        c = self.make(partitions=2)
        c.join("a"), c.join("b")
        sa, sb = c.sync("a"), c.sync("b")
        pa, pb = list(sa["assign"])[0], list(sb["assign"])[0]
        c.submit("a", _contrib({pa: [0, 5]}, wm=1200, span=_span(1)))
        c.submit("b", _contrib({pb: [0, 5]}, wm=300, span=_span(1)))
        assert c._m["commit_wm"].value() == 300.0
        assert c._m["member_wm"].value(member="a") == 1200.0
        assert c._m["wm_skew"].value(member="a") == 0.0
        assert c._m["wm_skew"].value(member="b") == 900.0

    def test_slo_histograms_observe_on_merge(self):
        c = self.make(partitions=1)
        b0, _ = c._m["barrier_s"].value()
        # submit->merge is member-labeled (r15: so a fenced member's
        # series can be removed instead of freezing)
        s0, _ = c._m["sub2merge_s"].value(member="a")
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 10]}, wm=900,
                               closed={300: _wagg_win(7, 50)},
                               span=_span(1)))
        b1, _ = c._m["barrier_s"].value()
        s1, _ = c._m["sub2merge_s"].value(member="a")
        assert b1 == b0 + 1
        assert s1 >= s0 + 1

    def test_rebalance_duration_observed_when_settled(self):
        c = self.make(partitions=2)
        n0, _ = c._m["rebalance_s"].value(reason="join")
        c.join("a")
        c.sync("a")  # acquires both partitions -> settled
        n1, _ = c._m["rebalance_s"].value(reason="join")
        assert n1 == n0 + 1
        # from a settled state, a fence opens a new timeline under its
        # own reason; a join landing mid-flight keeps the FIRST trigger
        # (the duration measures the whole disturbance)
        d0, _ = c._m["rebalance_s"].value(reason="death")
        c.fence("a")
        c.join("b")
        c.sync("b")  # b acquires everything -> settled under "death"
        d1, _ = c._m["rebalance_s"].value(reason="death")
        assert d1 == d0 + 1


class TestFenceFlightRecorderDump:
    def _patch_tmp(self, monkeypatch, tmp_path):
        import tempfile

        monkeypatch.setattr(tempfile, "gettempdir",
                            lambda: str(tmp_path))
        return os.path.join(str(tmp_path),
                            f"flowtrace-coordinator-{os.getpid()}.json")

    def test_zombie_rejection_dumps_with_span_context(
            self, monkeypatch, tmp_path):
        """Satellite regression (crash-restart path): a fenced member's
        replayed submission is rejected AND leaves a coordinator-side
        flight-recorder dump whose ring contains the rejection span
        with the zombie's own span context (sub id, chunk, send
        anchor)."""
        path = self._patch_tmp(monkeypatch, tmp_path)
        TRACER.configure("ring")
        c = MeshCoordinator([_wagg_spec()], 1)
        c.join("a")
        c.sync("a")
        c.fence("a")  # death: dump #1
        assert os.path.exists(path)
        os.unlink(path)
        span = _span(5, chunk=33)
        r = c.submit("a", _contrib({0: [0, 10]}, wm=900, span=span))
        assert not r["ok"]
        assert os.path.exists(path), \
            "zombie rejection must leave the post-mortem dump"
        with open(path) as f:
            doc = json.load(f)
        rejects = [e for e in doc["traceEvents"]
                   if e["name"] == "mesh_submit_reject"]
        assert rejects, "the rejected submission's span must be in it"
        args = rejects[-1]["args"]
        assert args["member"] == "a"
        assert args["sub"] == 5 and args["chunk"] == 33
        assert args["sent"] == pytest.approx(span["sent"])
        assert args["reason"] == "fenced"

    def test_rejoin_while_fenced_alive_dumps(self, monkeypatch,
                                             tmp_path):
        path = self._patch_tmp(monkeypatch, tmp_path)
        TRACER.configure("ring")
        c = MeshCoordinator([_wagg_spec()], 1)
        c.join("a")
        c.sync("a")
        c.join("a")  # crash-restart before expiry: fence + dump
        assert os.path.exists(path)

    def test_no_dump_when_tracing_off(self, monkeypatch, tmp_path):
        path = self._patch_tmp(monkeypatch, tmp_path)
        TRACER.configure("off")
        c = MeshCoordinator([_wagg_spec()], 1)
        c.join("a")
        c.sync("a")
        c.fence("a")
        assert not os.path.exists(path)

    def test_graceful_leave_does_not_dump(self, monkeypatch, tmp_path):
        path = self._patch_tmp(monkeypatch, tmp_path)
        TRACER.configure("ring")
        c = MeshCoordinator([_wagg_spec()], 1)
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 10]}, wm=900, final=True,
                               span=_span(1)))
        c.leave("a")
        assert not os.path.exists(path)


# ---------------------------------------------------------------------------
# /healthz + /debug endpoints
# ---------------------------------------------------------------------------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, json.loads(resp.read().decode())


class TestHealthz:
    def test_metrics_server_healthz_and_trace_now(self):
        server = MetricsServer(port=0).start()
        try:
            status, doc = _get_json(
                f"http://127.0.0.1:{server.port}/healthz")
            assert status == 200 and doc == {"ok": True}
            t0 = time.time()
            _, trace = _get_json(
                f"http://127.0.0.1:{server.port}/debug/trace")
            # the clock stamp the meshscope aggregator estimates from
            assert abs(trace["otherData"]["now"] - t0) < 60
        finally:
            server.stop()

    def test_coordinator_server_healthz(self):
        c = MeshCoordinator([_wagg_spec()], 1)
        server = MeshCoordinatorServer(c, port=0).start()
        try:
            status, doc = _get_json(
                f"http://127.0.0.1:{server.port}/healthz")
            assert status == 200 and doc["ok"] is True
        finally:
            server.stop()

    def test_member_state_server_healthz(self):
        class _Dummy:
            def _query_state(self, model):
                return None

        server = MemberStateServer(_Dummy(), port=0).start()
        try:
            status, doc = _get_json(
                f"http://127.0.0.1:{server.port}/healthz")
            assert status == 200 and doc == {"ok": True}
        finally:
            server.stop()


class _FakeTraceEndpoint:
    """A member-shaped /debug/trace endpoint whose clock runs at a
    configurable skew — what the coordinator's aggregator must align."""

    def __init__(self, skew_s: float, span_name: str = "member_span"):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                now = time.time() + outer.skew
                body = json.dumps({
                    "traceEvents": [{
                        "name": outer.span_name, "ph": "X",
                        "ts": round(now * 1e6, 1), "dur": 100.0,
                        "pid": 77, "tid": "w",
                    }],
                    "otherData": {"mode": "ring", "dropped_spans": 0,
                                  "now": now},
                }).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self.skew = skew_s
        self.span_name = span_name
        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}/debug/trace"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class TestAggregatedMeshTrace:
    def test_fan_out_aligns_skewed_member_clock(self):
        TRACER.configure("ring")
        c = MeshCoordinator([_wagg_spec()], 1)
        fake = _FakeTraceEndpoint(skew_s=120.0)
        server = MeshCoordinatorServer(c, port=0).start()
        try:
            c.join("w0", trace_url=fake.url)
            with TRACER.span("coord_probe"):
                pass
            _, doc = _get_json(
                f"http://127.0.0.1:{server.port}/debug/trace")
        finally:
            server.stop()
            fake.stop()
        lanes = {l["name"]: l for l in doc["otherData"]["lanes"]}
        assert set(lanes) == {"coordinator", "w0"}
        # the 120s skew was estimated from the fetch round-trip and
        # removed: the member span lands within the fetch RTT of the
        # coordinator's wall clock, not two minutes ahead
        ev = next(e for e in doc["traceEvents"]
                  if e["name"] == "member_span")
        assert abs(ev["ts"] / 1e6 - time.time()) < 30
        assert lanes["w0"]["clock_offset_ms"] == \
            pytest.approx(120_000.0, abs=5_000)
        # both lanes present with distinct pids
        pids = {e["pid"] for e in doc["traceEvents"]
                if e["name"] == "process_name"}
        assert len(pids) == 2
        probes = [e for e in doc["traceEvents"]
                  if e["name"] == "coord_probe"]
        assert probes

    def test_heartbeat_estimate_wins_over_fetch(self):
        """A member that reported a clock offset via sync() is aligned
        by THAT estimate (tighter: min-RTT of 16 heartbeats), not by
        the one-shot fetch."""
        TRACER.configure("ring")
        c = MeshCoordinator([_wagg_spec()], 1)
        fake = _FakeTraceEndpoint(skew_s=50.0)
        server = MeshCoordinatorServer(c, port=0).start()
        try:
            c.join("w0", trace_url=fake.url)
            # member-measured: coordinator - member = -50s exactly
            c.sync("w0", clock={"offset": -50.0, "rtt": 0.001})
            _, doc = _get_json(
                f"http://127.0.0.1:{server.port}/debug/trace")
        finally:
            server.stop()
            fake.stop()
        lanes = {l["name"]: l for l in doc["otherData"]["lanes"]}
        assert lanes["w0"]["clock_offset_ms"] == pytest.approx(50_000.0)
        assert lanes["w0"]["rtt_ms"] == pytest.approx(1.0)

    def test_unreachable_member_degrades_not_blacks_out(self):
        TRACER.configure("ring")
        c = MeshCoordinator([_wagg_spec()], 1)
        fake = _FakeTraceEndpoint(skew_s=0.0)
        dead_url = fake.url
        fake.stop()  # now nothing listens there
        server = MeshCoordinatorServer(c, port=0).start()
        try:
            c.join("w0", trace_url=dead_url)
            _, doc = _get_json(
                f"http://127.0.0.1:{server.port}/debug/trace")
        finally:
            server.stop()
        lanes = [l["name"] for l in doc["otherData"]["lanes"]]
        assert lanes == ["coordinator"]

    def test_lineage_endpoint_serves_records(self):
        c = MeshCoordinator([_wagg_spec()], 1)
        server = MeshCoordinatorServer(c, port=0).start()
        try:
            c.join("a")
            c.sync("a")
            c.submit("a", _contrib({0: [0, 10]}, wm=900,
                                   closed={300: _wagg_win(7, 50)},
                                   span=_span(1)))
            _, recs = _get_json(
                f"http://127.0.0.1:{server.port}/debug/lineage"
                f"?model=flows_5m&slot=300")
            _, all_recs = _get_json(
                f"http://127.0.0.1:{server.port}/debug/lineage")
            status, _ = _get_json(
                f"http://127.0.0.1:{server.port}/debug/lineage"
                f"?model=nope")
        finally:
            server.stop()
        assert len(recs) == 1
        assert recs[0]["model"] == "flows_5m"
        assert recs[0]["status"] == "merged"
        assert recs[0]["members"] == ["a"]
        assert len(all_recs) >= 1
        assert status == 200  # unknown model -> empty list, not error


# ---------------------------------------------------------------------------
# flow_build_info
# ---------------------------------------------------------------------------


class TestBuildInfo:
    def test_publish_sets_identity_labels(self):
        from flow_pipeline_tpu import native as native_lib

        TRACER.configure("ring")
        g = publish_build_info("coordinator")
        caps = native_lib.capabilities()
        native = ",".join(sorted(f for f, ok in caps.items() if ok)) \
            or "none"
        assert g.value(role="coordinator", native=native, trace="ring",
                       sketch="device", hh_sketch="table") == 1.0
        assert "flow_build_info" in REGISTRY.render()

    def test_worker_publishes_on_construction(self):
        StreamWorker(consumer=None, models={},
                     config=WorkerConfig(sketch_backend="device"))
        g = REGISTRY.gauge(*BUILD_INFO)
        rendered = g.render()
        assert 'role="worker"' in rendered
        assert 'sketch="device"' in rendered
        assert 'trace="' in rendered and 'native="' in rendered

    def test_member_inner_worker_identifies_as_member(self):
        """Review regression: a member process must publish ONE
        identity — the inner StreamWorker's gauge says role=member
        (MeshMember rewrites build_role), not a second role=worker
        series next to it."""
        from flow_pipeline_tpu.mesh import MeshMember

        m = MeshMember("w9", coordinator=None,
                       consumer_factory=lambda parts: None,
                       model_factory=dict,
                       config=WorkerConfig(sketch_backend="device"))
        assert m.config.build_role == "member"


# ---------------------------------------------------------------------------
# lineage CLI
# ---------------------------------------------------------------------------


class TestLineageCLI:
    def test_flags_registered(self):
        for flag in ("lineage.model", "lineage.slot", "lineage.raw"):
            assert flag in KNOWN_FLAGS

    def _serve_one_merged_window(self):
        c = MeshCoordinator([_wagg_spec()], 1)
        server = MeshCoordinatorServer(c, port=0).start()
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 10]}, wm=900,
                               closed={300: _wagg_win(7, 50)},
                               span=_span(4, chunk=2)))
        return c, server

    def test_summary_output(self, capsys):
        from flow_pipeline_tpu.cli import main

        c, server = self._serve_one_merged_window()
        try:
            rc = main(["lineage", "-mesh.coordinator",
                       f"http://127.0.0.1:{server.port}"])
        finally:
            server.stop()
        assert rc == 0
        out = capsys.readouterr().out
        assert "flows_5m @ 300 [merged]" in out
        assert "members=a" in out
        assert "sub=4" in out
        assert "0:[0,10)" in out

    def test_raw_json_output(self, capsys):
        from flow_pipeline_tpu.cli import main

        c, server = self._serve_one_merged_window()
        try:
            rc = main(["lineage", "-mesh.coordinator",
                       f"http://127.0.0.1:{server.port}",
                       "-lineage.raw", "-lineage.model", "flows_5m"])
        finally:
            server.stop()
        assert rc == 0
        records = json.loads(capsys.readouterr().out)
        assert records[0]["slot"] == 300
        assert records[0]["contributions"][0]["sub"] == 4


# ---------------------------------------------------------------------------
# end-to-end: in-process mesh lineage + churn trace ring parity
# ---------------------------------------------------------------------------


def _vals(*extra):
    fs = _processor_flags(_gen_flags(_common_flags(FlagSet("test"))))
    return fs.parse([
        "-produce.profile", "zipf", "-zipf.keys", str(N_KEYS),
        "-model.ports=false", "-model.ddos=false", "-model.ips=false",
        "-processor.batch", str(BATCH), "-sketch.capacity", "512",
        *extra,
    ])


def _stream_batches(n_flows=N_FLOWS, seed=0):
    gen = FlowGenerator(ZipfProfile(n_keys=N_KEYS, alpha=1.2),
                        seed=seed, rate=100_000.0)
    out, done = [], 0
    while done < n_flows:
        n = min(8192, n_flows - done)
        out.append(gen.batch(n))
        done += n
    return out


def _make_bus(n_flows=N_FLOWS, partitions=PARTITIONS):
    bus = InProcessBus()
    bus.create_topic("flows", partitions)
    for batch in _stream_batches(n_flows):
        produce_sharded(bus, "flows", batch, partitions)
    return bus


class ListSink:
    def __init__(self):
        self.tables = {}

    def write(self, table, rows):
        self.tables.setdefault(table, []).append(rows)


def _fold_flows5m(tables):
    acc = {}
    for rows in tables.get("flows_5m", []):
        for i in range(len(rows["timeslot"])):
            key = (int(rows["timeslot"][i]), int(rows["src_as"][i]),
                   int(rows["dst_as"][i]), int(rows["etype"][i]))
            v = acc.setdefault(key, np.zeros(3, np.uint64))
            v += np.array([rows["bytes"][i], rows["packets"][i],
                           rows["count"][i]], np.uint64)
    return acc


def _run_churn_mesh(vals, sink, monkeypatch_tmp=None):
    """The test_mesh churn leg: 3 workers, kill one mid-stream."""
    mesh = InProcessMesh(
        _make_bus(), "flows", 3,
        model_factory=lambda: _build_models(vals),
        config=WorkerConfig(poll_max=BATCH, snapshot_every=0),
        sinks=[sink], submit_every=2)
    mesh.start()
    victim = mesh.members[1]
    deadline = time.time() + 120
    while time.time() < deadline:
        w = victim.worker
        carry = mesh.coordinator._carry.get(victim.member_id)
        # kill only once a progress carry for an OPEN window is
        # accepted: the death then deterministically promotes a real
        # mid-window carry (the span-continuity story under test)
        if w is not None and w.flows_seen >= BATCH and \
                carry and carry.get("windows"):
            break
        time.sleep(0.002)
    else:
        pytest.fail("victim never got a carry accepted")
    mesh.kill_member(1)
    mesh.wait_idle()
    mesh.finalize()
    return mesh


def test_inprocess_4worker_trace_has_coordinator_and_member_lanes():
    """Acceptance: a 4-worker in-process mesh run with tracing on
    yields ONE aggregated Chrome trace through the coordinator's
    /debug/trace containing the coordinator protocol spans and every
    member's spans (in-process the member lanes are the per-member
    thread tracks of the single process lane; clocks are trivially
    aligned — the HTTP fan-out tests cover cross-process skew)."""
    vals = _vals()
    TRACER.configure("ring")
    mesh = InProcessMesh(
        _make_bus(), "flows", 4,
        model_factory=lambda: _build_models(vals),
        config=WorkerConfig(poll_max=BATCH, snapshot_every=0),
        sinks=[ListSink()])
    server = MeshCoordinatorServer(mesh.coordinator, port=0).start()
    try:
        mesh.run()
        _, doc = _get_json(
            f"http://127.0.0.1:{server.port}/debug/trace")
    finally:
        server.stop()
    tids = {e.get("tid") for e in doc["traceEvents"]}
    for i in range(4):
        assert f"mesh-w{i}" in tids, f"member w{i} lane missing"
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"mesh_submit", "mesh_submit_accept", "mesh_merge",
            "mesh_emit", "mesh_barrier_wait"} <= names
    assert [l["name"] for l in doc["otherData"]["lanes"]] == \
        ["coordinator"]


def test_mesh_lineage_answers_for_every_merged_window():
    """Acceptance: /debug/lineage answers for EVERY merged (model,
    slot) of an in-process mesh run — members, offset ranges, merge
    wall — and the lineage members match the mesh's live set."""
    vals = _vals()
    sink = ListSink()
    mesh = InProcessMesh(
        _make_bus(), "flows", 2,
        model_factory=lambda: _build_models(vals),
        config=WorkerConfig(poll_max=BATCH, snapshot_every=0),
        sinks=[sink])
    mesh.run()
    c = mesh.coordinator
    merged_keys = set(c.merged)
    assert merged_keys, "nothing merged — the leg is vacuous"
    records = {(r["model"], r["slot"]): r for r in c.lineage()
               if r["status"] == "merged"}
    for key in merged_keys:
        rec = records.get(key)
        assert rec is not None, f"no lineage for merged window {key}"
        assert rec["members"], key
        assert set(rec["members"]) <= {"w0", "w1"}
        assert rec["merge_wall_s"] >= 0.0
        assert rec["rows"] >= 0
        # every non-empty contribution names its offset ranges
        assert any(con["ranges"] for con in rec["contributions"])
    # SLO surfaces moved: barrier + submit->merge observed
    assert c._m["barrier_s"].value()[0] >= len(merged_keys)


def test_mesh_churn_ring_trace_continuity_and_bitexact(monkeypatch,
                                                       tmp_path):
    """Satellite: the trace ring under mesh churn. The kill-one-worker
    leg runs with -obs.trace=off and again with ring; sink output must
    be bit-exact across modes (instrumentation is observational), and
    the ring must hold the span story of the carry promotion: the
    victim's submits, the fence, the promotion, and the merge of the
    promoted window."""
    import tempfile

    monkeypatch.setattr(tempfile, "gettempdir", lambda: str(tmp_path))
    vals = _vals()
    TRACER.configure("off")
    sink_off = ListSink()
    _run_churn_mesh(vals, sink_off)
    TRACER.configure("ring")
    sink_ring = ListSink()
    mesh = _run_churn_mesh(vals, sink_ring)
    spans = TRACER.snapshot()
    # bit-exact sink parity off vs ring
    f_off, f_ring = _fold_flows5m(sink_off.tables), \
        _fold_flows5m(sink_ring.tables)
    assert set(f_off) == set(f_ring)
    for k in f_off:
        assert (f_off[k] == f_ring[k]).all()
    t_off = sink_off.tables["top_talkers"][0]
    t_ring = sink_ring.tables["top_talkers"][0]
    v_off = np.asarray(t_off["valid"])
    v_ring = np.asarray(t_ring["valid"])
    assert int(v_off.sum()) == int(v_ring.sum())
    for col in ("src_addr", "bytes", "packets", "count", "timeslot"):
        assert (np.asarray(t_off[col])[v_off] ==
                np.asarray(t_ring[col])[v_ring]).all(), col
    # span continuity across the carry promotion
    names = {}
    for name, t0, t1, thread, chunk, args in spans:
        names.setdefault(name, []).append(args or {})
    assert "mesh_fence" in names
    promos = names.get("mesh_carry_promotion", [])
    assert promos, "the kill must promote a carry"
    assert promos[0]["member"] == "w1"
    assert promos[0]["sub"] is not None  # the dead member's span ids survive
    # the victim submitted before death AND the merge story completed
    submit_members = {a["member"] for a in names.get("mesh_submit", [])}
    assert "w1" in submit_members
    accept_members = {a["member"]
                      for a in names.get("mesh_submit_accept", [])}
    assert accept_members >= {"w0", "w2"}  # survivors kept contributing
    merged_models = {a["model"] for a in names.get("mesh_merge", [])}
    assert {"flows_5m", "top_talkers"} <= merged_models
    # the promoted window's lineage chains to the merge
    promoted = [r for r in mesh.coordinator.lineage()
                if r["carries_promoted"]]
    assert promoted and all(r["status"] == "merged" for r in promoted
                            if r["status"] != "pending")
    # the kill also left the coordinator-side post-mortem dump
    dump = os.path.join(
        str(tmp_path), f"flowtrace-coordinator-{os.getpid()}.json")
    assert os.path.exists(dump)
