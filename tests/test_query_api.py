"""Live query API tests: O(K) top-K / open windows / alerts served straight
off a running worker's models."""

import json
import urllib.error
import urllib.request

import pytest

from flow_pipeline_tpu.engine import StreamWorker, WindowedHeavyHitter, WorkerConfig
from flow_pipeline_tpu.engine.query_api import QueryServer
from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile, ZipfProfile
from flow_pipeline_tpu.models import (
    DDoSConfig,
    DDoSDetector,
    HeavyHitterConfig,
    WindowAggConfig,
    WindowAggregator,
)
from flow_pipeline_tpu.sink import MemorySink
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer


@pytest.fixture
def served_worker():
    bus = InProcessBus()
    bus.create_topic("flows", 1)
    gen = FlowGenerator(ZipfProfile(n_keys=100, alpha=1.3), seed=91,
                        t0=1_699_999_800, rate=50.0)
    prod = Producer(bus, fixedlen=True)
    for _ in range(4):
        prod.send_many(gen.batch(500).to_messages())
    worker = StreamWorker(
        Consumer(bus, fixedlen=True),
        {
            "flows_5m": WindowAggregator(WindowAggConfig(batch_size=512)),
            "top_talkers": WindowedHeavyHitter(
                HeavyHitterConfig(batch_size=512, width=1 << 12, capacity=64),
                k=10,
            ),
            "ddos_alerts": DDoSDetector(DDoSConfig(batch_size=512,
                                                   n_buckets=256)),
        },
        [MemorySink()],
        WorkerConfig(snapshot_every=0),
    )
    while worker.run_once():  # drain the bus but do NOT finalize: the open
        pass  # window must stay live, which is what the API exists to serve
    server = QueryServer(worker, port=0).start()
    yield worker, server
    server.stop()


def get(server, path):
    return json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{path}"
        ).read()
    )


class TestQueryAPI:
    def test_healthz(self, served_worker):
        worker, server = served_worker
        h = get(server, "/healthz")
        assert h["ok"] and h["flows_seen"] == 2000
        assert set(h["models"]) == {"flows_5m", "top_talkers", "ddos_alerts"}

    def test_topk_open_window(self, served_worker):
        worker, server = served_worker
        t = get(server, "/topk?k=5")
        assert t["model"] == "top_talkers"
        assert t["window_start"] is not None
        assert 0 < len(t["rows"]) <= 5
        row = t["rows"][0]
        assert row["src_addr"].startswith("2001:db8:0:1::")
        assert row["bytes"] > 0

    def test_windows(self, served_worker):
        worker, server = served_worker
        w = get(server, "/windows")
        assert w["model"] == "flows_5m"
        assert w["watermark"] > 0
        assert w["open_windows"]  # something still open after the stream

    def test_alerts_empty_on_steady(self, served_worker):
        worker, server = served_worker
        assert get(server, "/alerts")["alerts"] == []

    def test_errors(self, served_worker):
        worker, server = served_worker
        with pytest.raises(urllib.error.HTTPError) as e:
            get(server, "/nope")
        assert e.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e:
            get(server, "/topk?model=flows_5m")  # wrong model kind
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            get(server, "/topk?model=ghost")
        assert e.value.code == 400

    @pytest.mark.parametrize("path", ["/topk?k=abc", "/alerts?limit=x",
                                      "/topk?k=1.5"])
    def test_malformed_query_params_are_400_json(self, served_worker,
                                                 path):
        """Malformed query params answer a 400 JSON error, never a
        handler traceback — the same contract the mesh server got in
        r12 (the reply path is the shared obs.server.reply_json)."""
        worker, server = served_worker
        with pytest.raises(urllib.error.HTTPError) as e:
            get(server, path)
        assert e.value.code == 400
        assert "error" in json.loads(e.value.read())
