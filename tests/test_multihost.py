"""DCN multi-host tests (SURVEY.md §2: "DCN for multi-host").

Two layers:
- LocalShardFeeder's single-process path on the 8-device CPU mesh, fed
  into a real sharded model (the code path every worker uses).
- A genuine 2-process jax.distributed bootstrap over loopback, each
  process contributing its local shard of a global array and running a
  cross-process collective — the smallest real DCN-shaped exercise that
  can run without two hosts.
"""

import os
import socket
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest

from flow_pipeline_tpu.parallel import make_mesh
from flow_pipeline_tpu.parallel.multihost import LocalShardFeeder

REPO = os.path.join(os.path.dirname(__file__), "..")


class TestLocalShardFeederSingleProcess:
    @pytest.fixture(scope="class")
    def mesh(self):
        assert len(jax.devices()) == 8
        return make_mesh()

    def test_feed_shards_rows_over_mesh(self, mesh):
        feeder = LocalShardFeeder(mesh)
        n = 64  # 8 rows per device
        cols = {
            "bytes": np.arange(n, dtype=np.uint64),
            "src_addr": np.tile(np.arange(4, dtype=np.uint32), (n, 1)),
        }
        valid = np.ones(n, bool)
        out, v = feeder.feed_columns(cols, valid)
        assert out["bytes"].shape == (n,)
        assert out["src_addr"].shape == (n, 4)
        # row-sharded: each of the 8 devices holds one 8-row shard
        assert len(out["bytes"].sharding.device_set) == 8
        shard = next(iter(out["bytes"].addressable_shards))
        assert shard.data.shape == (8,)
        np.testing.assert_array_equal(np.asarray(out["bytes"]), cols["bytes"])
        np.testing.assert_array_equal(np.asarray(v), valid)

    def test_fed_arrays_drive_sharded_model(self, mesh):
        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
        from flow_pipeline_tpu.models import HeavyHitterConfig
        from flow_pipeline_tpu.models.oracle import topk_exact
        from flow_pipeline_tpu.parallel import ShardedHeavyHitter

        config = HeavyHitterConfig(batch_size=256, width=1 << 10, capacity=32)
        model = ShardedHeavyHitter(config, mesh)
        feeder = LocalShardFeeder(mesh)
        g = FlowGenerator(ZipfProfile(n_keys=40, alpha=1.6), seed=77)
        batch = g.batch(2048)
        # feed through the multihost placement path instead of device_put
        padded, mask = batch.pad_to(2048)
        cols = padded.device_columns(
            ["src_addr", "dst_addr", "bytes", "packets"]
        )
        fed, valid = feeder.feed_columns(
            {k: np.asarray(v) for k, v in cols.items()}, np.asarray(mask)
        )
        model.update_device_columns(fed, valid)
        oracle = topk_exact(batch, ["src_addr", "dst_addr"], 1)
        top = model.top(1)
        assert (top["src_addr"][0] == oracle["src_addr"][0]).all()


WORKER_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, {repo!r})
    from flow_pipeline_tpu.utils.platform import force_cpu
    force_cpu()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flow_pipeline_tpu.parallel import make_mesh
    from flow_pipeline_tpu.parallel.multihost import (
        LocalShardFeeder, init_distributed)

    pid = int(sys.argv[1])
    port = sys.argv[2]
    init_distributed(f"127.0.0.1:{{port}}", 2, pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4  # 2 local x 2 processes
    assert len(jax.local_devices()) == 2
    mesh = make_mesh()
    feeder = LocalShardFeeder(mesh)
    # each "host" contributes its own half of the global batch
    local = np.full(8, float(pid + 1), np.float32)
    cols, valid = feeder.feed_columns({{"x": local}}, np.ones(8, bool))
    x = cols["x"]
    assert x.shape == (16,)  # global rows = both hosts' halves
    assert len(x.addressable_shards) == 2  # only this host's devices
    total = float(jax.jit(jnp.sum)(x))  # cross-process collective
    assert total == 8 * 1 + 8 * 2, total
    print("MULTIHOST_OK", pid, total, flush=True)
""")


class TestTwoProcessDistributed:
    def test_bootstrap_feed_and_collective(self, tmp_path):
        port = _free_port()
        script = tmp_path / "worker.py"
        script.write_text(WORKER_SCRIPT.format(repo=os.path.abspath(REPO)))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # worker sets its own device count
        # jax.distributed.initialize must run before ANY backend init, so
        # the workers get a bare interpreter: no inherited PYTHONPATH (a
        # sitecustomize there could eagerly register a backend — this
        # environment has one) and no user site. The script inserts the
        # repo itself into sys.path.
        env["PYTHONPATH"] = ""
        env["PYTHONNOUSERSITE"] = "1"
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(pid), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            )
            for pid in (0, 1)
        ]
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("distributed worker timed out")
            outs.append(out)
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {pid} failed:\n{out}"
            assert f"MULTIHOST_OK {pid} 24.0" in out


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
