"""DCN multi-host tests (SURVEY.md §2: "DCN for multi-host").

Two layers:
- LocalShardFeeder's single-process path on the 8-device CPU mesh, fed
  into a real sharded model (the code path every worker uses).
- A genuine 2-process jax.distributed bootstrap over loopback, each
  process contributing its local shard of a global array and running a
  cross-process collective — the smallest real DCN-shaped exercise that
  can run without two hosts.
"""

import os
import socket
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest

from flow_pipeline_tpu.parallel import make_mesh
from flow_pipeline_tpu.parallel.multihost import LocalShardFeeder

REPO = os.path.join(os.path.dirname(__file__), "..")


class TestLocalShardFeederSingleProcess:
    @pytest.fixture(scope="class")
    def mesh(self):
        assert len(jax.devices()) == 8
        return make_mesh()

    def test_feed_shards_rows_over_mesh(self, mesh):
        feeder = LocalShardFeeder(mesh)
        n = 64  # 8 rows per device
        cols = {
            "bytes": np.arange(n, dtype=np.uint64),
            "src_addr": np.tile(np.arange(4, dtype=np.uint32), (n, 1)),
        }
        valid = np.ones(n, bool)
        out, v = feeder.feed_columns(cols, valid)
        assert out["bytes"].shape == (n,)
        assert out["src_addr"].shape == (n, 4)
        # row-sharded: each of the 8 devices holds one 8-row shard
        assert len(out["bytes"].sharding.device_set) == 8
        shard = next(iter(out["bytes"].addressable_shards))
        assert shard.data.shape == (8,)
        np.testing.assert_array_equal(np.asarray(out["bytes"]), cols["bytes"])
        np.testing.assert_array_equal(np.asarray(v), valid)

    def test_fed_arrays_drive_sharded_model(self, mesh):
        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
        from flow_pipeline_tpu.models import HeavyHitterConfig
        from flow_pipeline_tpu.models.oracle import topk_exact
        from flow_pipeline_tpu.parallel import ShardedHeavyHitter

        config = HeavyHitterConfig(batch_size=256, width=1 << 10, capacity=32)
        model = ShardedHeavyHitter(config, mesh)
        feeder = LocalShardFeeder(mesh)
        g = FlowGenerator(ZipfProfile(n_keys=40, alpha=1.6), seed=77)
        batch = g.batch(2048)
        # feed through the multihost placement path instead of device_put
        padded, mask = batch.pad_to(2048)
        cols = padded.device_columns(
            ["src_addr", "dst_addr", "bytes", "packets", "sampling_rate"]
        )
        fed, valid = feeder.feed_columns(
            {k: np.asarray(v) for k, v in cols.items()}, np.asarray(mask)
        )
        model.update_device_columns(fed, valid)
        oracle = topk_exact(batch, ["src_addr", "dst_addr"], 1)
        top = model.top(1)
        assert (top["src_addr"][0] == oracle["src_addr"][0]).all()


WORKER_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, {repo!r})
    from flow_pipeline_tpu.utils.platform import force_cpu
    force_cpu()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from flow_pipeline_tpu.parallel import make_mesh
    from flow_pipeline_tpu.parallel.multihost import (
        LocalShardFeeder, init_distributed)

    pid = int(sys.argv[1])
    port = sys.argv[2]
    init_distributed(f"127.0.0.1:{{port}}", 2, pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4  # 2 local x 2 processes
    assert len(jax.local_devices()) == 2
    mesh = make_mesh()
    feeder = LocalShardFeeder(mesh)
    # each "host" contributes its own half of the global batch
    local = np.full(8, float(pid + 1), np.float32)
    cols, valid = feeder.feed_columns({{"x": local}}, np.ones(8, bool))
    x = cols["x"]
    assert x.shape == (16,)  # global rows = both hosts' halves
    assert len(x.addressable_shards) == 2  # only this host's devices
    total = float(jax.jit(jnp.sum)(x))  # cross-process collective
    assert total == 8 * 1 + 8 * 2, total
    print("MULTIHOST_OK", pid, total, flush=True)
""")


E2E_SCRIPT = textwrap.dedent("""
    import json, os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    sys.path.insert(0, {repo!r})
    from flow_pipeline_tpu.utils.platform import force_cpu
    force_cpu()
    import jax
    import numpy as np
    from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
    from flow_pipeline_tpu.models import HeavyHitterConfig, WindowAggConfig
    from flow_pipeline_tpu.parallel import make_mesh
    from flow_pipeline_tpu.parallel.multihost import (
        MultihostPipeline, init_distributed)

    pid = int(sys.argv[1]); port = sys.argv[2]
    phase = sys.argv[3]; ckpt = sys.argv[4]; outdir = sys.argv[5]
    init_distributed(f"127.0.0.1:{{port}}", 2, pid)
    mesh = make_mesh()  # 4 devices = 2 local x 2 processes
    PER_CHIP, N_BATCHES = 128, 8
    GLOBAL, HALF = PER_CHIP * 4, PER_CHIP * 2

    pipe = MultihostPipeline(
        mesh,
        WindowAggConfig(batch_size=PER_CHIP),
        {{"top_pairs": HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr"), batch_size=PER_CHIP,
            width=1 << 10, capacity=64)}},
        k=20,
    )
    start = 0
    if phase == "resume":
        start = pipe.restore(os.path.join(ckpt, str(pid)))
        assert start == 5, start  # batch 5 was processed but unsnapshotted

    # both processes derive the identical global stream (seeded); each
    # consumes its own contiguous half — the consumer-group partition split
    gen = FlowGenerator(ZipfProfile(n_keys=30, alpha=1.4), seed=5, t0=9000)
    batches = [gen.batch(GLOBAL) for _ in range(N_BATCHES)]
    COLS = ("time_received", "src_as", "dst_as", "etype", "bytes",
            "packets", "src_addr", "dst_addr", "sampling_rate")
    mine = slice(pid * HALF, (pid + 1) * HALF)
    for i in range(start, N_BATCHES):
        cols = batches[i].device_columns(COLS)
        local = {{k: np.ascontiguousarray(np.asarray(v)[mine])
                 for k, v in cols.items()}}
        wm = int(batches[i].columns["time_received"].max())
        pipe.update(local, np.ones(HALF, bool), wm)
        if phase == "first":
            if i == 4:
                pipe.snapshot(os.path.join(ckpt, str(pid)))
                # barrier: both snapshots must be durable before either
                # process may crash (the hot path has NO collectives, so
                # the processes are otherwise free-running)
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices("snapshot-durable")
                print("SNAPSHOT_DONE", pid, flush=True)
            if i == 5:  # crash mid-window, AFTER an unsnapshotted batch
                print("KILLED", pid, flush=True)
                os._exit(0)

    rows = pipe.flush(force=True)
    f5 = rows["flows_5m"]
    with open(os.path.join(outdir, f"flows5m_{{pid}}.json"), "w") as f:
        json.dump({{k: np.asarray(v).tolist() for k, v in f5.items()}}, f)
    if pid == 0:  # replicated merged top-K: identical on both, write once
        top = rows["top_pairs"]
        with open(os.path.join(outdir, "top.json"), "w") as f:
            json.dump({{k: np.asarray(v).tolist() for k, v in top.items()}},
                      f)
    print("MULTIHOST_E2E_OK", pid, flush=True)
""")


def _skip_if_backend_cannot_multiprocess(outs) -> None:
    """Old jax builds (<=0.4.x) cannot run multi-process collectives on
    the CPU backend at all — the child dies inside XLA with this exact
    message. That's an environment limit, not a regression in the
    distributed program (newer jax runs these green); skip instead of
    failing so the suite stays meaningful on both."""
    for out in outs:
        if "Multiprocess computations aren't implemented on the CPU" in out:
            pytest.skip("installed jax cannot run multi-process CPU "
                        "collectives (XLA INVALID_ARGUMENT)")


def _run_procs(script, phase, ckpt, outdir, port, nprocs=2,
               expect_crash=False, timeout=300):
    """Launch ``nprocs`` jax.distributed worker processes of ``script``
    and collect their outputs (generalized from the original 2-process
    pair runner; the host-loss test runs 4 then 3)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = ""
    env["PYTHONNOUSERSITE"] = "1"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port), phase,
             str(ckpt), str(outdir), str(nprocs)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        for pid in range(nprocs)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"{phase} worker timed out")
        outs.append(out)
    _skip_if_backend_cannot_multiprocess(outs)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if expect_crash:
            # one process os._exit()s first and the other may be torn
            # down by coordinator loss — nonzero exits are the scenario;
            # what matters is that both passed the snapshot barrier
            assert f"SNAPSHOT_DONE {pid}" in out, \
                f"{phase} worker {pid} never snapshotted:\n{out}"
        else:
            assert p.returncode == 0, f"{phase} worker {pid} failed:\n{out}"
    return outs


def _run_pair(script, phase, ckpt, outdir, port, expect_crash=False):
    return _run_procs(script, phase, ckpt, outdir, port, nprocs=2,
                      expect_crash=expect_crash, timeout=240)


class TestTwoProcessWorkerE2E:
    """VERDICT r2 #4: the FULL loop across 2 jax.distributed processes —
    per-host feed, sharded exact + sketch models, cross-process window
    merge, host-partial emission, and a kill-and-resume mid-window with an
    unsnapshotted batch that must replay exactly once."""

    def test_kill_resume_oracle_exact(self, tmp_path):
        script = tmp_path / "worker_e2e.py"
        script.write_text(E2E_SCRIPT.format(repo=os.path.abspath(REPO)))
        ckpt = tmp_path / "ckpt"
        outdir = tmp_path / "out"
        ckpt.mkdir()
        outdir.mkdir()

        outs = _run_pair(script, "first", ckpt, outdir, _free_port(),
                         expect_crash=True)
        assert any(f"KILLED {pid}" in out
                   for pid, out in enumerate(outs))
        assert (ckpt / "0").is_dir() and (ckpt / "1").is_dir()
        assert not list(outdir.iterdir())  # crashed before any emission

        outs = _run_pair(script, "resume", ckpt, outdir, _free_port())
        for pid, out in enumerate(outs):
            assert f"MULTIHOST_E2E_OK {pid}" in out

        import json

        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
        from flow_pipeline_tpu.models.oracle import exact_groupby
        from flow_pipeline_tpu.schema.batch import FlowBatch

        gen = FlowGenerator(ZipfProfile(n_keys=30, alpha=1.4), seed=5,
                            t0=9000)
        full = FlowBatch.concat([gen.batch(512) for _ in range(8)])

        # flows_5m: host-partial rows from BOTH processes, merged by key,
        # must equal the exact oracle over the whole stream — no row lost
        # to the crash, none double-counted by the replay
        merged: dict[tuple, np.ndarray] = {}
        for pid in (0, 1):
            rows = json.loads((outdir / f"flows5m_{pid}.json").read_text())
            for i in range(len(rows["timeslot"])):
                key = (rows["timeslot"][i], rows["src_as"][i],
                       rows["dst_as"][i], rows["etype"][i])
                acc = merged.setdefault(key, np.zeros(3, np.uint64))
                acc += np.array([rows["bytes"][i], rows["packets"][i],
                                 rows["count"][i]], np.uint64)
        oracle = exact_groupby(full, ["src_as", "dst_as", "etype"],
                               timeslot=True)
        want = {
            (int(oracle["timeslot"][i]), int(oracle["src_as"][i]),
             int(oracle["dst_as"][i]), int(oracle["etype"][i])):
            (int(oracle["bytes"][i]), int(oracle["packets"][i]),
             int(oracle["count"][i]))
            for i in range(len(oracle["timeslot"]))
        }
        got = {k: tuple(int(x) for x in v) for k, v in merged.items()}
        assert got == want
        assert sum(v[2] for v in got.values()) == len(full)

        # top-K: the replicated cross-process merge must carry exact
        # per-key table sums (capacity 64 > 30 keys: nothing evicted)
        top = json.loads((outdir / "top.json").read_text())
        got_top = {}
        for i in range(len(top["valid"])):
            if not top["valid"][i]:
                continue
            key = (tuple(top["src_addr"][i]), tuple(top["dst_addr"][i]))
            got_top[key] = (int(top["bytes"][i]), int(top["packets"][i]),
                            int(top["count"][i]))
        pairs = exact_groupby(full, ["src_addr", "dst_addr"])
        src = np.asarray(pairs["src_addr"]).reshape(len(pairs["bytes"]), -1)
        dst = np.asarray(pairs["dst_addr"]).reshape(len(pairs["bytes"]), -1)
        want_top = {
            (tuple(int(x) for x in src[i]), tuple(int(x) for x in dst[i])):
            (int(pairs["bytes"][i]), int(pairs["packets"][i]),
             int(pairs["count"][i]))
            for i in range(len(pairs["bytes"]))
        }
        # the emitted top-20 rows must each match the oracle exactly, and
        # the oracle's 20 heaviest pairs must all be present
        for key, vals in got_top.items():
            assert want_top[key] == vals
        heaviest = sorted(want_top, key=lambda k: -want_top[k][0])[:20]
        assert set(heaviest) == set(got_top)


REBALANCE_SCRIPT = textwrap.dedent("""
    import json, os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, {repo!r})
    from flow_pipeline_tpu.utils.platform import force_cpu
    force_cpu()
    import jax
    import numpy as np
    from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
    from flow_pipeline_tpu.models import HeavyHitterConfig, WindowAggConfig
    from flow_pipeline_tpu.parallel import make_mesh
    from flow_pipeline_tpu.parallel.multihost import (
        MultihostPipeline, init_distributed, reassign_lost_partitions)

    pid = int(sys.argv[1]); port = sys.argv[2]
    phase = sys.argv[3]; ckpt = sys.argv[4]; outdir = sys.argv[5]
    nprocs = int(sys.argv[6])
    N_PARTS, PER_CHIP, N_BATCHES, SNAP_AT = 4, 128, 8, 3
    GLOBAL = PER_CHIP * N_PARTS
    init_distributed(f"127.0.0.1:{{port}}", nprocs, pid)
    mesh = make_mesh()  # 1 local device per process

    pipe = MultihostPipeline(
        mesh,
        WindowAggConfig(batch_size=PER_CHIP),
        {{"top_pairs": HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr"), batch_size=PER_CHIP,
            width=1 << 10, capacity=64)}},
        k=20,
    )

    # every process derives the identical global stream; partition p is
    # the p-th contiguous row-quarter of each global batch
    gen = FlowGenerator(ZipfProfile(n_keys=30, alpha=1.4), seed=11, t0=9000)
    batches = [gen.batch(GLOBAL) for _ in range(N_BATCHES)]
    COLS = ("time_received", "src_as", "dst_as", "etype", "bytes",
            "packets", "src_addr", "dst_addr", "sampling_rate")
    def part_slice(b, part):
        cols = batches[b].device_columns(COLS)
        sl = slice(part * PER_CHIP, (part + 1) * PER_CHIP)
        return {{k: np.ascontiguousarray(np.asarray(v)[sl])
                for k, v in cols.items()}}
    # watermark may be passed eagerly: no flush happens until the final
    # force-flush, and update() only records the max
    wm = max(int(b.columns["time_received"].max()) for b in batches)

    if phase == "first":
        # 4 processes; each ingests its own partition for SNAP_AT batches.
        # Processes 0-2 snapshot (committing offsets 0..SNAP_AT-1);
        # process 3 is then permanently lost with NOTHING durable — its
        # committed offset stays 0, so the whole partition must replay.
        for b in range(SNAP_AT):
            pipe.update(part_slice(b, pid), np.ones(PER_CHIP, bool), wm)
        if pid != 3:
            pipe.snapshot(os.path.join(ckpt, str(pid)))
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("snapshots-durable")
        print("SNAPSHOT_DONE", pid, flush=True)
        if pid == 3:
            # hard kill AFTER the barrier (a pre-barrier kill would hang
            # the others inside the collective): this host never returns,
            # and nothing of it is durable
            print("LOST", pid, flush=True)
            os._exit(0)
        sys.exit(0)

    # phase == "rebalance": the 3 survivors form a NEW world (nprocs=3),
    # restore their own durable state, and re-consume the dead host's
    # partition from its committed offset (0) — round-robined by the
    # deterministic pure reassignment every survivor computes alone.
    start = pipe.restore(os.path.join(ckpt, str(pid)))
    assert start == SNAP_AT, start
    survivors = list(range(nprocs))
    assign = reassign_lost_partitions({{3: 0}}, survivors, N_BATCHES)
    worklists = {{s: [(s, b) for b in range(SNAP_AT, N_BATCHES)] + assign[s]
                 for s in survivors}}
    rounds = max(len(w) for w in worklists.values())
    zero = {{k: np.zeros_like(v) for k, v in part_slice(0, 0).items()}}
    mine = worklists[pid]
    for r in range(rounds):
        if r < len(mine):
            part, b = mine[r]
            pipe.update(part_slice(b, part), np.ones(PER_CHIP, bool), wm)
        else:  # padding round: all-invalid rows keep the collective shape
            pipe.update(zero, np.zeros(PER_CHIP, bool), wm)

    rows = pipe.flush(force=True)
    f5 = rows["flows_5m"]
    with open(os.path.join(outdir, f"flows5m_{{pid}}.json"), "w") as f:
        json.dump({{k: np.asarray(v).tolist() for k, v in f5.items()}}, f)
    if pid == 0:  # replicated merged top-K: identical on every survivor
        top = rows["top_pairs"]
        with open(os.path.join(outdir, "top.json"), "w") as f:
            json.dump({{k: np.asarray(v).tolist() for k, v in top.items()}},
                      f)
    print("REBALANCE_OK", pid, flush=True)
""")


class TestReassignLostPartitions:
    """The pure rebalance rule itself — runs everywhere (no collectives)."""

    def test_round_robin_from_committed_offsets(self):
        from flow_pipeline_tpu.parallel.multihost import (
            reassign_lost_partitions,
        )

        out = reassign_lost_partitions({3: 0}, [0, 1, 2], 8)
        # 8 orphan slices round-robined: deterministic, disjoint, complete
        assert out[0] == [(3, 0), (3, 3), (3, 6)]
        assert out[1] == [(3, 1), (3, 4), (3, 7)]
        assert out[2] == [(3, 2), (3, 5)]

    def test_committed_offsets_not_replayed(self):
        from flow_pipeline_tpu.parallel.multihost import (
            reassign_lost_partitions,
        )

        out = reassign_lost_partitions({5: 6, 7: 8}, [1, 2], 8)
        got = sorted(sl for w in out.values() for sl in w)
        # partition 5 replays only batches >= its committed offset 6;
        # partition 7 was fully durable — nothing to replay
        assert got == [(5, 6), (5, 7)]

    def test_every_survivor_computes_identical_maps(self):
        from flow_pipeline_tpu.parallel.multihost import (
            reassign_lost_partitions,
        )

        maps = [reassign_lost_partitions({2: 1, 3: 4}, [0, 1], 6)
                for _ in range(3)]
        assert maps[0] == maps[1] == maps[2]


class TestPermanentHostLoss:
    """VERDICT r5 #5: 4 jax.distributed processes, one killed PERMANENTLY
    (nothing durable), the 3 survivors restart as a smaller world and
    re-consume the dead host's partition from its committed offset —
    merged output must be oracle-exact over the full stream: nothing
    lost with the dead host, nothing double-counted by the replay."""

    def test_survivors_reconsume_lost_partition(self, tmp_path):
        script = tmp_path / "worker_loss.py"
        script.write_text(REBALANCE_SCRIPT.format(repo=os.path.abspath(REPO)))
        ckpt = tmp_path / "ckpt"
        outdir = tmp_path / "out"
        ckpt.mkdir()
        outdir.mkdir()

        outs = _run_procs(script, "first", ckpt, outdir, _free_port(),
                          nprocs=4)
        assert any("LOST 3" in out for out in outs)
        for pid in (0, 1, 2):
            assert (ckpt / str(pid)).is_dir()
        assert not (ckpt / "3").exists()  # the lost host left nothing
        assert not list(outdir.iterdir())

        outs = _run_procs(script, "rebalance", ckpt, outdir, _free_port(),
                          nprocs=3)
        for pid, out in enumerate(outs):
            assert f"REBALANCE_OK {pid}" in out

        import json

        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
        from flow_pipeline_tpu.models.oracle import exact_groupby
        from flow_pipeline_tpu.schema.batch import FlowBatch

        gen = FlowGenerator(ZipfProfile(n_keys=30, alpha=1.4), seed=11,
                            t0=9000)
        full = FlowBatch.concat([gen.batch(512) for _ in range(8)])

        # flows_5m host-partial rows from the 3 survivors, merged by key,
        # must equal the exact oracle over ALL FOUR partitions' rows
        merged: dict[tuple, np.ndarray] = {}
        for pid in (0, 1, 2):
            rows = json.loads((outdir / f"flows5m_{pid}.json").read_text())
            for i in range(len(rows["timeslot"])):
                key = (rows["timeslot"][i], rows["src_as"][i],
                       rows["dst_as"][i], rows["etype"][i])
                acc = merged.setdefault(key, np.zeros(3, np.uint64))
                acc += np.array([rows["bytes"][i], rows["packets"][i],
                                 rows["count"][i]], np.uint64)
        oracle = exact_groupby(full, ["src_as", "dst_as", "etype"],
                               timeslot=True)
        want = {
            (int(oracle["timeslot"][i]), int(oracle["src_as"][i]),
             int(oracle["dst_as"][i]), int(oracle["etype"][i])):
            (int(oracle["bytes"][i]), int(oracle["packets"][i]),
             int(oracle["count"][i]))
            for i in range(len(oracle["timeslot"]))
        }
        got = {k: tuple(int(x) for x in v) for k, v in merged.items()}
        assert got == want
        # exact row conservation: the lost partition replayed exactly once
        assert sum(v[2] for v in got.values()) == len(full)

        # top-K (capacity 64 > 30 keys: nothing evicted -> exact sums)
        top = json.loads((outdir / "top.json").read_text())
        got_top = {}
        for i in range(len(top["valid"])):
            if not top["valid"][i]:
                continue
            key = (tuple(top["src_addr"][i]), tuple(top["dst_addr"][i]))
            got_top[key] = (int(top["bytes"][i]), int(top["packets"][i]),
                            int(top["count"][i]))
        pairs = exact_groupby(full, ["src_addr", "dst_addr"])
        src = np.asarray(pairs["src_addr"]).reshape(len(pairs["bytes"]), -1)
        dst = np.asarray(pairs["dst_addr"]).reshape(len(pairs["bytes"]), -1)
        want_top = {
            (tuple(int(x) for x in src[i]), tuple(int(x) for x in dst[i])):
            (int(pairs["bytes"][i]), int(pairs["packets"][i]),
             int(pairs["count"][i]))
            for i in range(len(pairs["bytes"]))
        }
        for key, vals in got_top.items():
            assert want_top[key] == vals
        heaviest = sorted(want_top, key=lambda k: -want_top[k][0])[:20]
        assert set(heaviest) == set(got_top)


class TestTwoProcessDistributed:
    def test_bootstrap_feed_and_collective(self, tmp_path):
        port = _free_port()
        script = tmp_path / "worker.py"
        script.write_text(WORKER_SCRIPT.format(repo=os.path.abspath(REPO)))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # worker sets its own device count
        # jax.distributed.initialize must run before ANY backend init, so
        # the workers get a bare interpreter: no inherited PYTHONPATH (a
        # sitecustomize there could eagerly register a backend — this
        # environment has one) and no user site. The script inserts the
        # repo itself into sys.path.
        env["PYTHONPATH"] = ""
        env["PYTHONNOUSERSITE"] = "1"
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(pid), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            )
            for pid in (0, 1)
        ]
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("distributed worker timed out")
            outs.append(out)
        _skip_if_backend_cannot_multiprocess(outs)
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {pid} failed:\n{out}"
            assert f"MULTIHOST_OK {pid} 24.0" in out


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
