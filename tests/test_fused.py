"""Fused-pipeline equivalence (engine.fused vs the per-model path).

The fused step shares one master sort across every prefix-keyed model and
one dst-keyed sort between the top-dst sketch and the DDoS accumulate; it
must be OUTPUT-IDENTICAL to the serial per-model path — same flows_5m
rows, same top-K tables, same DDoS alerts, same late-row drops. Window
lifecycles are driven host-side exactly like the unfused wrappers, so the
comparison covers slot rolls and late data too.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from flow_pipeline_tpu.engine import (
    FusedPipeline,
    StreamWorker,
    WindowedHeavyHitter,
    WorkerConfig,
)
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.models import (
    DDoSConfig,
    DDoSDetector,
    DenseTopConfig,
    DenseTopKModel,
    HeavyHitterConfig,
    WindowAggConfig,
    WindowAggregator,
)
from flow_pipeline_tpu.ops.segment import (
    presorted_groupby_float,
    sort_groupby_float,
    sort_rows_float,
)
from flow_pipeline_tpu.transport import Consumer, InProcessBus

WINDOW = 300
BS = 512


def make_models(sub_seconds: int, n_keys: int):
    """The cli's default model family at test scale (cli._build_models)."""
    def hh_cfg(key_cols):
        return HeavyHitterConfig(key_cols=key_cols, batch_size=BS,
                                 width=1 << 10, capacity=128)

    return {
        "flows_5m": WindowAggregator(WindowAggConfig(batch_size=BS)),
        "top_talkers": WindowedHeavyHitter(
            hh_cfg(("src_addr", "dst_addr", "src_port", "dst_port",
                    "proto")), k=50),
        "top_src_ips": WindowedHeavyHitter(hh_cfg(("src_addr",)), k=50),
        "top_dst_ips": WindowedHeavyHitter(hh_cfg(("dst_addr",)), k=50),
        "top_src_ports": WindowedHeavyHitter(
            DenseTopConfig(key_col="src_port", batch_size=BS), k=50,
            model_cls=DenseTopKModel),
        "ddos_alerts": DDoSDetector(DDoSConfig(
            n_buckets=1 << 10, sub_window_seconds=sub_seconds,
            warmup_windows=0, batch_size=BS)),
    }


def make_stream(n_keys: int = 100):
    """8 batches crossing 3 window slots, with late rows in batch 5."""
    gen = FlowGenerator(ZipfProfile(n_keys=n_keys, alpha=1.2), seed=7)
    t0 = 6000  # slot-aligned (6000 % 300 == 0)
    batches = []
    for i in range(8):
        b = gen.batch(BS)
        times = t0 + i * 90 + (np.arange(BS) % 30)
        if i == 5:
            times[:25] = t0  # two slots behind current by then: late
        b.columns["time_received"] = times.astype(np.uint64)
        batches.append(b)
    return batches


def drive_fused(models, batches):
    pipe = FusedPipeline(models)
    for b in batches:
        pipe.update(b)
    return models


def drive_serial(models, batches):
    for b in batches:
        for m in models.values():
            m.update(b)
    return models


def canon_rows(rows: dict) -> list[tuple]:
    """Columnar rows dict -> sorted list of per-row tuples."""
    names = sorted(rows)
    cols = [np.asarray(rows[n]).reshape(len(rows[names[0]]), -1)
            for n in names]
    return sorted(tuple(x for c in cols for x in c[i]) for i in
                  range(len(cols[0])))


def assert_same_windows(a: list[dict], b: list[dict], keys=None):
    assert len(a) == len(b)
    for wa, wb in zip(a, b):
        names = keys or sorted(set(wa) | set(wb))
        for name in names:
            np.testing.assert_array_equal(
                np.asarray(wa[name]), np.asarray(wb[name]),
                err_msg=f"window column {name!r} diverged")


def test_prefix_groupby_matches_direct(rng):
    """Grouping presorted rows by a key PREFIX == sorting by that prefix
    directly (integer-valued floats: order-independent sums)."""
    keys = rng.integers(0, 5, size=(64, 3)).astype(np.uint32)
    vals = rng.integers(0, 100, size=(64, 2)).astype(np.float32)
    valid = rng.random(64) < 0.8
    sk, sv, sc = sort_rows_float(jnp.asarray(keys), jnp.asarray(vals),
                                 jnp.asarray(valid))
    for width in (1, 2, 3):
        got = presorted_groupby_float(sk, sv, sc, width)
        want = sort_groupby_float(jnp.asarray(keys[:, :width]),
                                  jnp.asarray(vals), jnp.asarray(valid))
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


class TestFusedEquivalence:
    def test_aligned_cadence_bit_exact(self):
        """DDoS cadence == window: identical chunking everywhere, so every
        output must match bit-for-bit (CMS estimates included)."""
        batches = make_stream()
        fused = drive_fused(make_models(WINDOW, 100), batches)
        serial = drive_serial(make_models(WINDOW, 100), batches)

        assert canon_rows(fused["flows_5m"].flush(True)) == \
            canon_rows(serial["flows_5m"].flush(True))
        for name in ("top_talkers", "top_src_ips", "top_dst_ips",
                     "top_src_ports"):
            assert_same_windows(fused[name].flush(True),
                                serial[name].flush(True))
            assert fused[name].late_flows_dropped == \
                serial[name].late_flows_dropped
        fa, sa = fused["ddos_alerts"], serial["ddos_alerts"]
        assert fa.late_flows_dropped == sa.late_flows_dropped
        assert len(fa.alerts) == len(sa.alerts)
        for x, y in zip(fa.alerts, sa.alerts):
            assert x.keys() == y.keys()
            for k in x:
                np.testing.assert_array_equal(np.asarray(x[k]),
                                              np.asarray(y[k]))

    def test_finer_ddos_cadence(self):
        """DDoS sub-windows finer than the sketch window: the fused path
        chunks hh updates at sub boundaries, so CMS *estimates* may take a
        different (equally valid) path — but exact outputs (flows_5m,
        dense ports, ddos, table sums with no eviction) must still match."""
        batches = make_stream(n_keys=100)  # 100 < capacity 128: no eviction
        fused = drive_fused(make_models(30, 100), batches)
        serial = drive_serial(make_models(30, 100), batches)

        assert canon_rows(fused["flows_5m"].flush(True)) == \
            canon_rows(serial["flows_5m"].flush(True))
        assert_same_windows(fused["top_src_ports"].flush(True),
                            serial["top_src_ports"].flush(True))
        for name in ("top_talkers", "top_src_ips", "top_dst_ips"):
            exact = ["timeslot", "bytes", "packets", "count", "valid",
                     *fused[name].config.key_cols]
            assert_same_windows(fused[name].flush(True),
                                serial[name].flush(True), keys=exact)
        fa, sa = fused["ddos_alerts"], serial["ddos_alerts"]
        assert len(fa.alerts) == len(sa.alerts)
        for x, y in zip(fa.alerts, sa.alerts):
            for k in x:
                np.testing.assert_array_equal(np.asarray(x[k]),
                                              np.asarray(y[k]))

    def test_mixed_scale_col_dst_families_demoted(self):
        """Two dst-keyed sketch families with DIFFERENT scale_col: the
        shared B path scales planes by the first B config's rate, so the
        second family must be demoted to its own groupby — outputs must
        match the serial path for both (ADVICE r4)."""
        def models():
            return {
                "top_dst_ips": WindowedHeavyHitter(HeavyHitterConfig(
                    key_cols=("dst_addr",), batch_size=BS, width=1 << 10,
                    capacity=128), k=50),
                "top_dst_ips_raw": WindowedHeavyHitter(HeavyHitterConfig(
                    key_cols=("dst_addr",), batch_size=BS, width=1 << 10,
                    capacity=128, scale_col=None), k=50),
            }

        batches = make_stream()
        # vary the rate so a wrong scaling actually changes sums
        for i, b in enumerate(batches):
            b.columns["sampling_rate"] = np.full(BS, 1 + i % 3, np.uint64)
        fused = drive_fused(models(), batches)
        serial = drive_serial(models(), batches)
        for name in ("top_dst_ips", "top_dst_ips_raw"):
            assert_same_windows(fused[name].flush(True),
                                serial[name].flush(True))

    def test_unsupported_model_set_falls_back(self):
        class Opaque:
            def update(self, batch):
                pass

        assert not FusedPipeline.supported({"x": Opaque()})
        worker = StreamWorker(None, {"x": Opaque()},
                              config=WorkerConfig(fused=True))
        assert worker.fused is None


def test_worker_fused_vs_serial_sink_rows():
    """Integration: the same stream through two workers (fused on/off)
    lands identical flows_5m rows in the sink."""
    class CollectSink:
        def __init__(self):
            self.rows: dict[str, list] = {}

        def write(self, table, rows):
            self.rows.setdefault(table, []).append(rows)

    out = {}
    for fused in (True, False):
        from flow_pipeline_tpu.schema import wire

        bus = InProcessBus()
        bus.create_topic("flows", 1)
        for b in make_stream():
            for frame in wire.iter_raw_frames(b.to_wire()):
                bus.produce("flows", frame)
        sink = CollectSink()
        worker = StreamWorker(
            Consumer(bus, fixedlen=True),
            make_models(WINDOW, 100),
            [sink],
            WorkerConfig(poll_max=BS, snapshot_every=0, fused=fused),
        )
        assert (worker.fused is not None) == fused
        worker.run(stop_when_idle=True)
        rows = [canon_rows(r) for r in sink.rows.get("flows_5m", [])]
        out[fused] = sorted(sum(rows, []))
    assert out[True] == out[False]
