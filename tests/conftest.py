"""Test configuration.

Tests run on CPU with 8 virtual devices standing in for a v5e-8 (SURVEY.md §4:
multi-chip tests on CPU via xla_force_host_platform_device_count). Must be set
before jax is imported anywhere.
"""

import os

# Force CPU (shared helper: utils.platform documents why the env var alone
# is not enough in this environment). Two concurrent test runs must never
# race for the single real TPU chip. XLA_FLAGS must be set before import.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

from flow_pipeline_tpu.utils.platform import force_cpu

force_cpu()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
