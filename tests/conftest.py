"""Test configuration.

Tests run on CPU with 8 virtual devices standing in for a v5e-8 (SURVEY.md §4:
multi-chip tests on CPU via xla_force_host_platform_device_count). Must be set
before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
