"""Test configuration.

Tests run on CPU with 8 virtual devices standing in for a v5e-8 (SURVEY.md §4:
multi-chip tests on CPU via xla_force_host_platform_device_count). Must be set
before jax is imported anywhere.
"""

import os

# Force CPU (shared helper: utils.platform documents why the env var alone
# is not enough in this environment). Two concurrent test runs must never
# race for the single real TPU chip. XLA_FLAGS must be set before import.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

from flow_pipeline_tpu.utils.platform import force_cpu

force_cpu()

import threading

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


# Worker pipeline threads (PipelinedExecutor / PrefetchConsumer /
# AsyncFlusher) are daemons, so a test that drains a worker with
# run_once() and never calls finalize() leaks them silently — and a
# leaked prefetch poller keeps hitting the bus.poll FAULTS seam
# forever, polluting any later test that arms a fault plan on it.
_PIPELINE_THREADS = ("feed-prefetch", "ingest-group", "ingest-flush")


@pytest.fixture(autouse=True, scope="module")
def _reap_leaked_pipeline_threads():
    """Signal pipeline threads leaked by this module to exit."""
    yield
    for t in threading.enumerate():
        if t.name not in _PIPELINE_THREADS or not t.is_alive():
            continue
        # each thread target is a bound _run method; its owner exposes
        # the same stop signal stop() uses, minus the join/drain — a
        # leaked thread has nothing pending worth draining
        owner = getattr(getattr(t, "_target", None), "__self__", None)
        stop = getattr(owner, "_stop", None)
        if stop is not None:
            stop.set()
        jobs = getattr(owner, "_jobs", None)
        if jobs is not None:
            jobs.put(None)  # wake a flusher blocked on queue.get()
