"""Double-buffered feed (engine.prefetch): correctness of the wrap —
same batches, same offsets, same at-least-once protocol — plus the
threading contract (commits execute on the owner thread, flush_commits
barriers, stop drains)."""

import threading
import time

import numpy as np
import pytest

from flow_pipeline_tpu.engine import (
    PrefetchConsumer,
    StreamWorker,
    WindowedHeavyHitter,
    WorkerConfig,
)
from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile
from flow_pipeline_tpu.models import WindowAggConfig, WindowAggregator
from flow_pipeline_tpu.models.oracle import flows_5m
from flow_pipeline_tpu.schema.batch import FlowBatch
from flow_pipeline_tpu.sink import MemorySink
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer


def fill_bus(n=3000, partitions=2, seed=71):
    bus = InProcessBus()
    bus.create_topic("flows", partitions)
    gen = FlowGenerator(MockerProfile(), seed=seed, t0=1_699_999_800,
                        rate=20.0)
    batches = []
    prod = Producer(bus, fixedlen=True)
    for _ in range(n // 500):
        b = gen.batch(500)
        batches.append(b)
        prod.send_many(b.to_messages())
    return bus, FlowBatch.concat(batches)


class TestPrefetchConsumer:
    def test_same_batches_same_offsets(self):
        bus, _ = fill_bus(n=2000)
        plain = Consumer(bus, fixedlen=True, group="plain")
        pref = PrefetchConsumer(Consumer(bus, fixedlen=True, group="pref"),
                                depth=2, poll_max=512)
        def drain(c):
            out = []
            while True:
                b = c.poll(512)
                if b is None:
                    return out
                out.append(b)
        got_p = drain(plain)
        got_f = drain(pref)
        key = lambda bs: sorted(
            (b.partition, b.first_offset, b.last_offset, len(b))
            for b in bs
        )
        assert key(got_p) == key(got_f)
        pref.stop()

    def test_commit_executes_on_owner_thread_and_barriers(self):
        bus, _ = fill_bus(n=1000)
        inner = Consumer(bus, fixedlen=True)
        pref = PrefetchConsumer(inner, depth=2, poll_max=512)
        b = pref.poll(512)
        assert b is not None
        pref.commit(b.partition, b.last_offset + 1)
        pref.flush_commits()
        assert pref.committed(b.partition) == b.last_offset + 1
        pref.stop()

    def test_commit_before_first_poll_is_direct(self):
        bus, _ = fill_bus(n=500)
        pref = PrefetchConsumer(Consumer(bus, fixedlen=True), poll_max=512)
        pref.commit(0, 7)  # no thread yet: executes inline
        assert pref.committed(0) == 7

    def test_poll_blocks_through_first_fetch(self):
        # stop_when_idle callers must not see None just because the
        # thread hasn't finished its first fetch
        bus, _ = fill_bus(n=500)
        pref = PrefetchConsumer(Consumer(bus, fixedlen=True),
                                depth=1, poll_max=512, idle_sleep=0.01)
        assert pref.poll(512) is not None  # first call, thread cold
        pref.stop()

    def test_stop_drains_pending_commits(self):
        bus, _ = fill_bus(n=500)
        pref = PrefetchConsumer(Consumer(bus, fixedlen=True), poll_max=512)
        b = pref.poll(512)
        pref.commit(b.partition, b.last_offset + 1)
        pref.stop()
        assert pref.committed(b.partition) == b.last_offset + 1


class TestWorkerWithPrefetch:
    def test_parity_and_offsets(self):
        bus, all_flows = fill_bus(n=3000)
        sink = MemorySink()
        worker = StreamWorker(
            Consumer(bus, fixedlen=True),
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=512))},
            [sink],
            WorkerConfig(poll_max=512, snapshot_every=3, prefetch=2),
        )
        assert isinstance(worker.consumer, PrefetchConsumer)
        worker.run(stop_when_idle=True)
        # exact parity through the threaded feed
        oracle = flows_5m(all_flows)
        agg = {}
        for r in sink.tables["flows_5m"]:
            k = (r["timeslot"], r["src_as"], r["dst_as"], r["etype"])
            agg[k] = agg.get(k, 0) + r["count"]
        assert sum(agg.values()) == 3000
        assert len(agg) == len(oracle["timeslot"])
        # offsets fully committed after finalize (thread commits flushed)
        assert worker.consumer.lag() == 0

    def test_prefetch_zero_disables_wrap(self):
        bus, _ = fill_bus(n=500)
        worker = StreamWorker(
            Consumer(bus, fixedlen=True),
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=512))},
            [MemorySink()],
            WorkerConfig(poll_max=512, prefetch=0),
        )
        assert isinstance(worker.consumer, Consumer)

    def test_feed_overlaps_device_step(self):
        # while the worker is inside a (slow) model update, the feed
        # thread must already have the next batch queued
        bus, _ = fill_bus(n=2000)

        seen = []

        class SlowModel:
            def __init__(self, consumer_ref):
                self.consumer_ref = consumer_ref

            def update(self, batch):
                time.sleep(0.1)  # a slow device step
                seen.append(self.consumer_ref._batches.qsize())

            def flush(self, force=False):
                return {"timeslot": np.array([], np.uint64)}

        worker = StreamWorker(
            Consumer(bus, fixedlen=True), {}, [],
            WorkerConfig(poll_max=512, prefetch=2),
        )
        model = SlowModel(worker.consumer)
        worker.models = {"flows_5m": WindowAggregator(
            WindowAggConfig(batch_size=512))}
        worker.models["slow"] = model
        worker.run(stop_when_idle=True)
        # at least one mid-update snapshot of the queue saw work ready
        assert max(seen) >= 1


class TestPrefetchRobustness:
    def test_data_after_idle_still_seen(self):
        # sticky-idle regression: once the feed thread has gone idle, a
        # late publish must still be returned by the next poll (plain
        # Consumer semantics: poll reflects live bus state)
        bus, _ = fill_bus(n=500)
        pref = PrefetchConsumer(Consumer(bus, fixedlen=True),
                                depth=2, poll_max=512, idle_sleep=0.01)
        while pref.poll(512) is not None:
            pass  # exhaust; feed thread is now idle
        gen = FlowGenerator(MockerProfile(), seed=99, t0=1_699_999_800,
                            rate=20.0)
        Producer(bus, fixedlen=True).send_many(gen.batch(300).to_messages())
        got = 0
        while (b := pref.poll(512)) is not None:
            got += len(b)
        assert got == 300
        pref.stop()

    def test_crash_in_sink_stops_feed_thread(self):
        # a sink exception unwinding run() must not leak the feed thread
        bus, _ = fill_bus(n=1000)

        class BrokenSink:
            def write(self, table, rows):
                raise RuntimeError("sink down")

        worker = StreamWorker(
            Consumer(bus, fixedlen=True),
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=512))},
            [BrokenSink()],
            WorkerConfig(poll_max=512, prefetch=2),
        )
        with pytest.raises(RuntimeError, match="sink down"):
            worker.run(stop_when_idle=True)
        assert worker.consumer._thread is None  # stopped, not leaked

    def test_stop_timeout_keeps_ownership(self):
        # a feed thread stuck in a blocking inner.poll must not hand the
        # non-thread-safe consumer back to the caller
        release = threading.Event()

        entered = threading.Event()

        class BlockingConsumer:
            def __init__(self):
                self.commits = []

            def poll(self, max_messages):
                entered.set()
                release.wait(300)  # a broker stall
                return None

            def commit(self, partition, next_offset):
                self.commits.append((partition, next_offset))

        inner = BlockingConsumer()
        pref = PrefetchConsumer(inner, poll_max=512, idle_sleep=0.01)
        pref._start()  # poll() itself would block on the stalled fetch
        assert entered.wait(5)
        with pytest.raises(TimeoutError):
            pref.stop(timeout=0.2)
        pref.commit(0, 5)  # must route via the queue, not run inline
        assert inner.commits == []  # the stuck thread hasn't executed it
        release.set()  # un-stick; thread sees _stop and exits, draining
        pref._thread.join(5)
        assert inner.commits == [(0, 5)]

    def test_poll_error_surfaces_to_caller(self):
        # a poison message / dead broker must crash the caller (supervisor
        # restart semantics), not loop silently in the feed thread
        class PoisonConsumer:
            def poll(self, max_messages):
                raise ValueError("poison frame")

            def commit(self, partition, next_offset):
                pass

        pref = PrefetchConsumer(PoisonConsumer(), poll_max=512,
                                idle_sleep=0.01)
        with pytest.raises(ValueError, match="poison frame"):
            deadline = time.time() + 10
            while time.time() < deadline:
                pref.poll(512)

    def test_commit_error_surfaces_via_flush(self):
        # flush_commits must not report success for commits that never
        # reached the broker
        bus, _ = fill_bus(n=500)
        inner = Consumer(bus, fixedlen=True)
        broken = RuntimeError("group rebalanced")
        inner.commit = lambda p, o: (_ for _ in ()).throw(broken)
        pref = PrefetchConsumer(inner, poll_max=512, idle_sleep=0.01)
        b = pref.poll(512)
        pref.commit(b.partition, b.last_offset + 1)
        with pytest.raises(RuntimeError, match="group rebalanced"):
            pref.flush_commits()

    def test_single_poll_call_observes_late_error(self):
        # the error can land while the caller is already blocked inside
        # poll(); the dead-thread branch must surface it, not return None
        # (a None here turns a broker death into a clean end-of-stream)
        class LateExplodingConsumer:
            def poll(self, max_messages):
                time.sleep(0.05)  # caller is inside its get() by now
                raise OSError("broker died")

            def commit(self, partition, next_offset):
                pass

        pref = PrefetchConsumer(LateExplodingConsumer(), poll_max=512,
                                idle_sleep=0.01)
        with pytest.raises(OSError, match="broker died"):
            pref.poll(512)  # ONE call must observe it

    def test_flush_after_feed_death_raises_real_error_fast(self):
        # commits issued after the feed thread died must execute inline
        # and flush_commits must raise the original error, not stall for
        # its full timeout on a queue nobody drains
        class PoisonConsumer:
            def __init__(self):
                self.commits = []

            def poll(self, max_messages):
                raise ValueError("poison frame")

            def commit(self, partition, next_offset):
                self.commits.append((partition, next_offset))

        inner = PoisonConsumer()
        pref = PrefetchConsumer(inner, poll_max=512, idle_sleep=0.01)
        with pytest.raises(ValueError):
            pref.poll(512)
        pref.commit(0, 9)
        assert inner.commits == [(0, 9)]  # executed inline, thread dead
        t0 = time.time()
        with pytest.raises(ValueError, match="poison frame"):
            pref.flush_commits(timeout=30)
        assert time.time() - t0 < 5  # the real error, promptly
