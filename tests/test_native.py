"""Native C++ codec tests: bit-parity with the pure-Python codec (the
correctness reference), malformed-input handling, and the throughput
sanity bound. Skipped when libflowdecode.so is not built (`make native`)."""

import numpy as np
import pytest

from flow_pipeline_tpu import native
from flow_pipeline_tpu.schema import (
    FlowBatch,
    FlowMessage,
    FlowType,
    encode_frame,
    encode_stream,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="libflowdecode.so not built (make native)"
)


def make_msgs(n=500):
    return [
        FlowMessage(
            type=FlowType.SFLOW_5,
            time_received=1_700_000_000 + i,
            sampling_rate=1000,
            sequence_num=i,
            time_flow_start=1_700_000_000 + i,
            time_flow_end=1_700_000_001 + i,
            src_addr=bytes([i % 256]) * 16,
            dst_addr=b"\x00" * 12 + bytes([10, 0, i % 256, (i * 3) % 256]),
            sampler_address=b"\x00" * 12 + b"\x0a\x00\x00\x01",
            bytes=(i * 37) % 1500,
            packets=i % 100,
            src_as=65000 + i % 3,
            dst_as=65000 + (i * 2) % 3,
            in_if=i % 8,
            out_if=(i + 1) % 8,
            proto=6 if i % 2 else 17,
            src_port=1024 + i,
            dst_port=443,
            ip_tos=i % 4,
            ip_ttl=64,
            tcp_flags=0x18,
            etype=0x86DD,
            ipv6_flow_label=i,
            flow_direction=i % 2,
        )
        for i in range(n)
    ]


class TestDecodeParity:
    def test_columns_match_python_codec(self):
        msgs = make_msgs()
        wire_bytes = encode_stream(msgs)
        got = native.decode_stream(wire_bytes)
        want = FlowBatch.from_messages(msgs)
        assert len(got) == len(want)
        for name in want.columns:
            np.testing.assert_array_equal(
                got.columns[name], want.columns[name], err_msg=name
            )

    def test_uint64_fields_preserved(self):
        msgs = [FlowMessage(bytes=2**40, time_received=2**33)]
        got = native.decode_stream(encode_stream(msgs))
        assert got.columns["bytes"][0] == 2**40
        assert got.columns["time_received"][0] == 2**33

    def test_empty_and_default_frames(self):
        msgs = [FlowMessage(), FlowMessage(packets=1)]
        got = native.decode_stream(encode_stream(msgs))
        assert len(got) == 2
        assert got.columns["packets"].tolist() == [0, 1]

    def test_unknown_fields_skipped(self):
        # unused field 12 varint + field 13 bytes inside a frame
        body = bytes([12 << 3, 7, (13 << 3) | 2, 2, 0xAA, 0xBB])
        body += encode_stream([FlowMessage(packets=9)])[1:]  # strip its prefix
        frame = bytes([len(body)]) + body
        got = native.decode_stream(frame)
        assert got.columns["packets"][0] == 9

    def test_malformed_truncated(self):
        wire_bytes = encode_stream(make_msgs(3))
        with pytest.raises(ValueError):
            native.decode_stream(wire_bytes[:-2])

    def test_garbage(self):
        with pytest.raises(ValueError):
            native.decode_stream(b"\xff\xff\xff\xff")

    def test_huge_length_varint_rejected(self):
        # length-delimited field claiming 2^63 bytes must not wrap the
        # bounds check (signed-overflow hardening for untrusted streams)
        huge = bytes([0x80] * 8 + [0x80, 0x01])  # varint 2^63
        body = bytes([(6 << 3) | 2]) + huge  # field 6, wt 2
        frame = bytes([len(body)]) + body
        with pytest.raises(ValueError):
            native.decode_stream(frame)
        # same shape at the frame-length level
        with pytest.raises(ValueError):
            native.decode_stream(huge + b"\x00")

    def test_single_byte_frames_counted(self):
        # an all-default message frames to b"\x00": 1 byte per frame
        stream = b"\x00" * 100
        got = native.decode_stream(stream)
        assert len(got) == 100


class TestEncodeParity:
    def test_encode_matches_python(self):
        # start at i=1: row 0's src_addr would be all-zero, where the native
        # encoder legally omits the field (see native.encode_stream docstring)
        msgs = make_msgs(200)[1:]
        batch = FlowBatch.from_messages(msgs)
        assert native.encode_stream(batch) == encode_stream(msgs)

    def test_all_zero_address_omitted_but_equivalent(self):
        msgs = [FlowMessage(src_addr=b"\x00" * 16, packets=3)]
        batch = FlowBatch.from_messages(msgs)
        data = native.encode_stream(batch)
        assert len(data) < len(encode_stream(msgs))  # field omitted
        again = native.decode_stream(data)
        np.testing.assert_array_equal(
            again.columns["src_addr"], batch.columns["src_addr"]
        )
        assert again.columns["packets"][0] == 3

    def test_roundtrip_through_native_both_ways(self):
        batch = FlowBatch.from_messages(make_msgs(100))
        again = native.decode_stream(native.encode_stream(batch))
        for name in batch.columns:
            np.testing.assert_array_equal(
                again.columns[name], batch.columns[name], err_msg=name
            )


class TestThroughput:
    def test_native_beats_python_by_10x(self):
        import time

        from flow_pipeline_tpu.schema import wire as pywire

        msgs = make_msgs(2000)
        wire_bytes = encode_stream(msgs)
        t0 = time.perf_counter()
        native.decode_stream(wire_bytes)
        t_native = time.perf_counter() - t0
        t0 = time.perf_counter()
        FlowBatch.from_messages(pywire.decode_frames(wire_bytes))
        t_py = time.perf_counter() - t0
        assert t_py / t_native > 10
