"""Model-level tests: heavy-hitter top-K vs exact oracle (the <=1% error
gate from BASELINE.json) and DDoS spike detection on injected attacks."""

import numpy as np
import pytest

from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile, ZipfProfile
from flow_pipeline_tpu.models import (
    DDoSConfig,
    DDoSDetector,
    HeavyHitterConfig,
    HeavyHitterModel,
)
from flow_pipeline_tpu.models.oracle import topk_exact
from flow_pipeline_tpu.schema.batch import FlowBatch


def key_tuple(row_keys, i):
    return tuple(int(x) for x in np.atleast_1d(row_keys[i]).ravel())


class TestHeavyHitterParity:
    def run_model(self, config, batches):
        model = HeavyHitterModel(config)
        for b in batches:
            model.update(b)
        return model

    def oracle_top(self, batches, key_cols, k):
        return topk_exact(FlowBatch.concat(batches), list(key_cols), k)

    def test_addr_pair_topk_within_1pct(self):
        config = HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr"), batch_size=4096,
            width=1 << 14, capacity=512,
        )
        g = FlowGenerator(ZipfProfile(n_keys=2000, alpha=1.2), seed=31)
        batches = [g.batch(4096) for _ in range(6)]
        model = self.run_model(config, batches)
        k = 20
        top = model.top(k)
        oracle = self.oracle_top(batches, config.key_cols, k)

        got = {
            (key_tuple(top["src_addr"], i) + key_tuple(top["dst_addr"], i)):
                float(top["bytes"][i])
            for i in range(k)
        }
        errs = []
        for i in range(k):
            key = (key_tuple(oracle["src_addr"], i)
                   + key_tuple(oracle["dst_addr"], i))
            true = float(oracle["bytes"][i])
            assert key in got, f"oracle top-{k} key {i} missing from sketch"
            errs.append(abs(got[key] - true) / true)
        assert max(errs) <= 0.01, f"max top-K bytes error {max(errs):.4f}"

    def test_plain_admission_ab_leg_stays_accurate(self):
        # -sketch.admission=plain (the bench A/B baseline without the
        # CMS-seeded space-saving entry) must still place the oracle
        # top keys — with capacity >= distinct keys nothing is evicted,
        # so table sums are exact even without seeded admission
        config = HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr"), batch_size=2048,
            width=1 << 12, capacity=256, table_admission="plain",
        )
        g = FlowGenerator(ZipfProfile(n_keys=100, alpha=1.3), seed=13)
        batches = [g.batch(2048) for _ in range(4)]
        model = self.run_model(config, batches)
        top = model.top(5)
        oracle = self.oracle_top(batches, config.key_cols, 5)
        for i in range(5):
            assert (top["src_addr"][i] == oracle["src_addr"][i]).all()
            assert float(top["bytes"][i]) == float(oracle["bytes"][i])

    def test_bad_admission_rejected(self):
        config = HeavyHitterConfig(batch_size=256, width=1 << 10,
                                   capacity=32, table_admission="bogus")
        g = FlowGenerator(ZipfProfile(n_keys=10), seed=1)
        with pytest.raises(ValueError, match="table_admission"):
            HeavyHitterModel(config).update(g.batch(256))

    def test_five_tuple_talkers(self):
        config = HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr", "src_port", "dst_port", "proto"),
            batch_size=2048, width=1 << 14, capacity=256,
        )
        g = FlowGenerator(ZipfProfile(n_keys=500, alpha=1.4), seed=32)
        batches = [g.batch(2048) for _ in range(4)]
        model = self.run_model(config, batches)
        top = model.top(10)
        oracle = self.oracle_top(
            batches, config.key_cols, 10
        )
        # rank-0 talker identical, bytes within 1%
        got_key = (key_tuple(top["src_addr"], 0) + key_tuple(top["dst_addr"], 0)
                   + (int(top["src_port"][0]), int(top["dst_port"][0]),
                      int(top["proto"][0])))
        want_key = (key_tuple(oracle["src_addr"], 0)
                    + key_tuple(oracle["dst_addr"], 0)
                    + (int(oracle["src_port"][0]), int(oracle["dst_port"][0]),
                       int(oracle["proto"][0])))
        assert got_key == want_key
        err = abs(float(top["bytes"][0]) - float(oracle["bytes"][0])) / float(
            oracle["bytes"][0]
        )
        assert err <= 0.01

    def test_counts_and_packets_planes(self):
        config = HeavyHitterConfig(batch_size=1024, width=1 << 14, capacity=128)
        g = FlowGenerator(ZipfProfile(n_keys=100, alpha=1.5), seed=33)
        batches = [g.batch(1024) for _ in range(3)]
        model = self.run_model(config, batches)
        top = model.top(5)
        oracle = topk_exact(
            FlowBatch.concat(batches), ["src_addr", "dst_addr"], 5
        )
        # table sums for the hottest key are exact (never evicted)
        assert float(top["bytes"][0]) == float(oracle["bytes"][0])
        assert int(top["count"][0]) > 0
        # CMS estimate plane is an upper bound of the table sum
        assert float(top["bytes_est"][0]) >= float(top["bytes"][0]) - 1e-3

    def test_oversized_and_odd_batches_chunked(self):
        # update() must accept any batch size, not just config.batch_size
        config = HeavyHitterConfig(batch_size=512, width=1 << 12, capacity=64)
        g = FlowGenerator(ZipfProfile(n_keys=50, alpha=1.3), seed=35)
        big = g.batch(1337)  # > batch_size and not a multiple
        whole = HeavyHitterModel(config)
        whole.update(big)
        oracle = topk_exact(big, ["src_addr", "dst_addr"], 3)
        top = whole.top(3)
        assert float(top["bytes"][0]) == float(oracle["bytes"][0])

    def test_saturated_counters_stay_positive(self):
        # bytes >= 2^31 (int32-negative bit patterns) must rank first, not last
        from flow_pipeline_tpu.schema.message import FlowMessage

        msgs = [FlowMessage(bytes=3_000_000_000, packets=1,
                            src_addr=b"\x01" * 16, dst_addr=b"\x02" * 16)]
        msgs += [FlowMessage(bytes=100, packets=1,
                             src_addr=bytes([i]) * 16, dst_addr=b"\x09" * 16)
                 for i in range(3, 20)]
        batch = FlowBatch.from_messages(msgs)
        model = HeavyHitterModel(
            HeavyHitterConfig(batch_size=64, width=1 << 10, capacity=32)
        )
        model.update(batch)
        top = model.top(1)
        assert float(top["bytes"][0]) == 3_000_000_000.0

    def test_reset_clears_state(self):
        model = HeavyHitterModel(HeavyHitterConfig(batch_size=256, width=1 << 10, capacity=32))
        g = FlowGenerator(ZipfProfile(n_keys=50), seed=34)
        model.update(g.batch(256))
        model.reset()
        top = model.top(5)
        assert not top["valid"].any()


class TestDDoS:
    def make_traffic(self, seed, attack_dst=None, attack_mult=50):
        """Baseline mocker traffic; optionally one dst under attack in the
        last sub-windows."""
        g = FlowGenerator(MockerProfile(), seed=seed, t0=1_699_999_800, rate=200.0)
        batches = [g.batch(2000) for _ in range(8)]  # 80s = 8 sub-windows
        if attack_dst is not None:
            # amplify packets toward one dst in the final 2 sub-windows
            for b in batches[-2:]:
                dst = b.columns["dst_addr"]
                hit = (dst[:, 3] & 0xFF) == attack_dst
                b.columns["packets"][hit] = b.columns["packets"][hit] * attack_mult
        return batches

    def run(self, batches, config=None):
        det = DDoSDetector(config or DDoSConfig(batch_size=2048, n_buckets=1 << 10,
                                                sub_window_seconds=10))
        for b in batches:
            det.update(b)
        det.close_sub_window()
        return det

    def test_no_alert_on_steady_traffic(self):
        det = self.run(self.make_traffic(seed=41))
        assert det.alerts == []

    def test_attack_detected(self):
        det = self.run(self.make_traffic(seed=42, attack_dst=7))
        assert len(det.alerts) >= 1
        # alerted address ends with the attacked host byte
        assert any(int(a["dst_addr"][3]) & 0xFF == 7 for a in det.alerts)

    def test_alert_carries_scores(self):
        det = self.run(self.make_traffic(seed=43, attack_dst=9))
        a = det.alerts[0]
        assert a["zscore"] >= 4.0
        assert a["rate"] > a["baseline_quantile"]

    def test_boundary_straddling_batch_split(self):
        # one batch spanning two sub-windows must fold rates separately
        g = FlowGenerator(MockerProfile(), seed=44, t0=1_699_999_800, rate=100.0)
        det = DDoSDetector(DDoSConfig(batch_size=2048, n_buckets=256,
                                      sub_window_seconds=10))
        det.update(g.batch(1500))  # 15 seconds -> straddles one boundary
        assert det.folds == 1  # first sub-window closed by the straddle
        assert det.current_sub == 1_699_999_810

    def test_late_rows_dropped_not_accumulated(self):
        # rows for an already-closed sub-window must be dropped (and
        # counted), never folded into the CURRENT sub-window where they
        # would inflate rates and can fire spurious z-score alerts
        g = FlowGenerator(MockerProfile(), seed=45, t0=1_699_999_800, rate=100.0)
        det = DDoSDetector(DDoSConfig(batch_size=2048, n_buckets=256,
                                      sub_window_seconds=10))
        current = g.batch(1000)  # 10s, fills sub-window 0 exactly
        det.update(current)
        det.update(g.batch(500))  # advances into sub-window 1
        assert det.current_sub == 1_699_999_810
        rates_before = np.asarray(det.state.rates).copy()
        late = FlowBatch(
            {k: v[:200].copy() for k, v in current.columns.items()},
            current.partition,
        )
        late.columns["time_received"][:] = 1_699_999_805  # sub-window 0
        det.update(late)
        assert det.late_flows_dropped == 200
        np.testing.assert_array_equal(np.asarray(det.state.rates), rates_before)
        assert det.current_sub == 1_699_999_810  # no spurious close either

    def test_padding_rows_never_touch_last_bucket(self):
        # regression: -1 "drop" index used to wrap to bucket n_buckets-1
        import jax.numpy as jnp
        from flow_pipeline_tpu.models.ddos import ddos_accumulate, ddos_init
        from flow_pipeline_tpu.ops.quantile import QuantileSketchSpec

        config = DDoSConfig(batch_size=8, n_buckets=16)
        state = ddos_init(config, QuantileSketchSpec())
        state = state._replace(addrs=state.addrs.at[15].set(jnp.uint32(7)))
        cols = {
            "dst_addr": jnp.zeros((8, 4), jnp.int32),
            "packets": jnp.ones(8, jnp.int32),
            "sampling_rate": jnp.ones(8, jnp.int32),
        }
        state = ddos_accumulate(state, cols, jnp.zeros(8, bool), config=config)
        assert np.asarray(state.addrs)[15].tolist() == [7, 7, 7, 7]
        assert float(jnp.sum(state.rates)) == 0.0


class TestTablePrefilter:
    def test_accuracy_within_gate(self):
        # prefilter trades a looser Misra-Gries bound for a 4x smaller
        # merge sort; on a Zipf stream the top-K must still be right
        g = FlowGenerator(ZipfProfile(n_keys=400, alpha=1.3), seed=31)
        batches = [g.batch(2048) for _ in range(4)]
        tops = {}
        for pre in (False, True):
            m = HeavyHitterModel(HeavyHitterConfig(
                batch_size=512, width=1 << 12, capacity=64,
                table_prefilter=pre,
            ))
            for b in batches:
                m.update(b)
            tops[pre] = m.top(10)
        oracle = topk_exact(FlowBatch.concat(batches),
                            ["src_addr", "dst_addr"], 10)
        for pre in (False, True):
            top = tops[pre]
            for i in range(10):
                assert (top["src_addr"][i] == oracle["src_addr"][i]).all(), pre
                assert abs(int(top["bytes"][i]) - int(oracle["bytes"][i])) \
                    <= 0.01 * int(oracle["bytes"][i]) + 1, pre

    def test_selects_everything_when_uniques_fit(self):
        # batch slots (512) exceed 2*capacity (256) so the prefilter
        # branch RUNS, but distinct keys (~30) fit: the top-2C selection
        # must keep every valid group and match the unfiltered path
        g = FlowGenerator(ZipfProfile(n_keys=30, alpha=1.5), seed=32)
        batch = g.batch(512)
        tops = []
        for pre in (False, True):
            m = HeavyHitterModel(HeavyHitterConfig(
                batch_size=512, width=1 << 10, capacity=128,
                table_prefilter=pre,
            ))
            m.update(batch)
            tops.append(m.top(10))
        for k in tops[0]:
            np.testing.assert_array_equal(tops[0][k], tops[1][k])

    @staticmethod
    def _crafted_batch(src_keys: np.ndarray, bytes_: np.ndarray):
        """FlowBatch whose (src_addr, dst_addr) identity is src_keys and
        whose bytes are bytes_; everything else from the generator."""
        n = len(src_keys)
        g = FlowGenerator(ZipfProfile(n_keys=4), seed=0)
        b = g.batch(n)
        addr = np.zeros((n, 4), np.uint32)
        addr[:, 3] = src_keys
        b.columns["src_addr"] = addr
        b.columns["dst_addr"] = addr.copy()
        b.columns["bytes"] = bytes_.astype(np.uint64)
        b.columns["sampling_rate"] = np.ones(n, np.uint64)
        return b

    def test_resident_keys_never_starved(self):
        """The r4 regression (VERDICT #4): with per-batch distinct keys
        >> capacity, table-RESIDENT keys whose rows rank below the batch
        top-candidates lost every later increment (~25x under-count on
        near-uniform streams). The table-aware prefilter must accumulate
        residents exactly, like the unfiltered merge."""
        cap = 64
        rng = np.random.default_rng(34)
        # batch 1: keys 0..63 with heavy rows -> they become residents
        resid = np.repeat(np.arange(cap, dtype=np.uint32), 4)
        b1 = self._crafted_batch(resid, np.full(len(resid), 1000))
        # batches 2..5: residents appear with LOW-ranking rows, buried
        # under 500 fresh distinct keys per batch with big rows
        batches = [b1]
        for r in range(4):
            fresh = 1000 + rng.permutation(2000)[:500].astype(np.uint32)
            keys = np.concatenate([np.arange(cap, dtype=np.uint32), fresh])
            vals = np.concatenate([np.full(cap, 10), np.full(500, 500)])
            batches.append(self._crafted_batch(keys, vals))
        m = HeavyHitterModel(HeavyHitterConfig(
            batch_size=512, width=1 << 12, capacity=cap))
        for b in batches:
            m.update(b)
        top = m.top(cap)
        # every original resident must still be tracked with its EXACT
        # total: 4*1000 from batch 1 + 4 later rows of 10
        got = {int(k): int(v) for k, v in
               zip(top["src_addr"][:, 3], top["bytes"]) if v >= 4000}
        for key in range(cap):
            assert got.get(key) == 4040, (key, got.get(key))

    def test_near_uniform_stream_within_gate(self):
        """BASELINE's <=1% error gate on a near-uniform 64k-key stream
        with DEFAULT flags (prefilter on): the values reported for the
        top-20 keys must be within 1% of those keys' true totals —
        under the r4 prefilter they were ~4% of truth."""
        g = FlowGenerator(ZipfProfile(n_keys=65536, alpha=0.05), seed=33)
        batches = [g.batch(8192) for _ in range(8)]
        m = HeavyHitterModel(HeavyHitterConfig(
            batch_size=8192, width=1 << 16, capacity=1024))
        for b in batches:
            m.update(b)
        top = m.top(20)
        # true totals of the REPORTED keys (identity on a uniform stream
        # is arbitrary — honest VALUES for whatever is reported are not)
        allb = FlowBatch.concat(batches)
        src = allb.columns["src_addr"][:, 3].astype(np.uint64)
        dst = allb.columns["dst_addr"][:, 3].astype(np.uint64)
        flat = src << np.uint64(32) | dst
        want = {}
        for i in range(20):
            k = (np.uint64(top["src_addr"][i, 3]) << np.uint64(32)
                 | np.uint64(top["dst_addr"][i, 3]))
            want[i] = int(allb.columns["bytes"][flat == k].sum())
        for i in range(20):
            got = int(top["bytes"][i])
            assert abs(got - want[i]) <= 0.01 * want[i] + 1, \
                (i, got, want[i])


def drive_admission_rounds(rounds):
    """Assert the space-saving admission bounds over a candidate stream.

    ``rounds``: list of [(key, value), ...] batches. Uses a deliberately
    NARROW CMS (width 64, depth 2 — ~20x more keys than cells) so
    estimates over-state grossly and newcomers enter inflated, competing
    with residents at the eviction boundary. Asserts after every merge:

      (1) upper bound — every resident's table value >= its true total
          (admission seeds the CMS estimate covering pre-entry mass;
          residents take exact increments thereafter);
      (2) Misra-Gries dropped mass — every evicted resident leaves with
          tracked mass <= the minimum SURVIVING table value, so a key
          whose true total dominates the boundary cannot be displaced,
          over-estimated newcomers included (ops.topk.topk_merge_est's
          documented guarantee).

    Returns the number of resident evictions exercised, so callers can
    require the adversarial case actually occurred.
    """
    import jax
    import jax.numpy as jnp

    from flow_pipeline_tpu.ops import cms as cms_ops
    from flow_pipeline_tpu.ops import topk as topk_ops

    C, N, DEPTH, WIDTH = 8, 16, 2, 64
    cms = cms_ops.cms_init(1, DEPTH, WIDTH)
    tk, tv = topk_ops.topk_init(C, 1, 1)
    cms_add = jax.jit(cms_ops.cms_add_conservative)
    cms_query = jax.jit(cms_ops.cms_query)
    merge = jax.jit(topk_ops.topk_merge_est)
    sentinel = int(topk_ops.SENTINEL)

    def as_dict(keys, vals):
        return {int(k[0]): float(v[0]) for k, v in
                zip(np.asarray(keys), np.asarray(vals))
                if k[0] != sentinel}

    true: dict[int, float] = {}
    evictions = 0
    for pairs in rounds:
        sums: dict[int, float] = {}
        for k, v in pairs:
            sums[k] = sums.get(k, 0.0) + v
            true[k] = true.get(k, 0.0) + v
        uniq = np.full((N, 1), topk_ops.SENTINEL, np.uint32)
        vals = np.zeros((N, 1), np.float32)
        valid = np.zeros(N, bool)
        for i, (k, v) in enumerate(list(sums.items())[:N]):
            uniq[i, 0] = k
            vals[i, 0] = v
            valid[i] = True
        cms = cms_add(cms, jnp.asarray(uniq), jnp.asarray(vals),
                      jnp.asarray(valid))
        est = cms_query(cms, jnp.asarray(uniq))
        old = as_dict(tk, tv)
        tk, tv = merge(tk, tv, jnp.asarray(uniq), jnp.asarray(vals), est,
                       jnp.asarray(valid))
        table = as_dict(tk, tv)
        for k, v in table.items():
            assert v >= true[k] - 1e-3 * max(1.0, true[k]), \
                f"table under-counts key {k}: {v} < true {true[k]}"
        if table:
            boundary = min(table.values())
            for k, v in old.items():
                if k not in table:
                    evictions += 1
                    assert v <= boundary + 1e-3 * max(1.0, boundary), (
                        f"evicted resident {k} carried {v} past the "
                        f"rank-C boundary {boundary}")
    return evictions


class TestSpaceSavingAdmissionSeeded:
    """Seeded adversarial admission run (VERDICT r5 #5) — the same
    bounds test_property.py fuzzes with hypothesis, kept runnable in
    environments without it."""

    def test_bounds_hold_and_evictions_occur(self):
        rng = np.random.default_rng(3)
        rounds = []
        for _ in range(50):
            ks = rng.integers(1, 1200, size=rng.integers(1, 17))
            vs = rng.integers(1, 1000, size=len(ks))
            rounds.append([(int(k), float(v)) for k, v in zip(ks, vs)])
        evictions = drive_admission_rounds(rounds)
        # the adversarial case must actually be exercised, not vacuous
        assert evictions > 20
