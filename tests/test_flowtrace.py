"""flowtrace: per-chunk tracing, histogram metrics, in-kernel phase
attribution.

The contracts under test: (1) the flight recorder is a bounded,
lock-safe ring whose Chrome trace-event export is shape-stable (golden
file) and Perfetto-loadable (valid JSON, complete events, us
timestamps); (2) chunk ids minted at decode tie one chunk's spans
together ACROSS the feed/group/worker/flusher thread boundaries, live
via /debug/trace and post-mortem via the worker-error dump; (3) the
Histogram metric renders cumulative le-bucket series that aggregate
across instances, and the StageTimer's dynamically-named summary family
is capped; (4) the kernels' stats out-struct is purely observational —
bit-exact outputs with stats on vs off — and its counters are sane;
(5) recording survives concurrent scrape + mutation from many threads.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from flow_pipeline_tpu import native
from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.obs import MetricsRegistry, MetricsServer, REGISTRY
from flow_pipeline_tpu.obs.trace import TRACER, TraceRecorder
from flow_pipeline_tpu.obs.tracing import MAX_STAGES, StageTimer
from flow_pipeline_tpu.transport import Consumer

from test_fused import BS, WINDOW, make_models, make_stream
from test_ingest import CollectSink, _stream_to_bus

GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "flowtrace_golden.json")


@pytest.fixture
def tracer():
    """A fresh, isolated recorder (tests must not depend on — or
    pollute — the process-wide TRACER's contents)."""
    return TraceRecorder(capacity=8, mode="ring")


class TestTraceRecorder:
    def test_mode_validation(self, tracer):
        with pytest.raises(ValueError, match="off|ring|always"):
            tracer.configure("sometimes")

    def test_off_records_nothing(self, tracer):
        tracer.configure("off")
        tracer.record("x", 0.0, 1.0)
        with tracer.span("y"):
            pass
        assert tracer.snapshot() == []
        assert tracer.chrome_trace()["traceEvents"] == []

    def test_ring_bounds_and_overwrites_oldest(self, tracer):
        for i in range(20):
            tracer.record("s", float(i), float(i) + 0.5, chunk=i)
        snap = tracer.snapshot()
        assert len(snap) == 8  # capacity, not 20
        # oldest-first, and the survivors are the LAST 8 recorded
        assert [ev[4] for ev in snap] == list(range(12, 20))
        assert tracer.chrome_trace()["otherData"]["dropped_spans"] == 12

    def test_always_retains_everything(self, tracer):
        tracer.configure("always")
        for i in range(100):
            tracer.record("s", 0.0, 1.0, chunk=i)
        assert len(tracer.snapshot()) == 100

    def test_configure_resets_state(self, tracer):
        tracer.record("s", 0.0, 1.0)
        tracer.configure("ring")
        assert tracer.snapshot() == []

    def test_span_records_thread_and_args(self, tracer):
        with tracer.span("work", chunk=3, rows=10):
            pass
        (name, t0, t1, thread, chunk, args), = tracer.snapshot()
        assert name == "work" and chunk == 3
        assert t1 >= t0
        assert thread == threading.current_thread().name
        assert args == {"rows": 10}

    def test_span_records_on_exception(self, tracer):
        with pytest.raises(RuntimeError):
            with tracer.span("boom", chunk=1):
                raise RuntimeError("x")
        assert [ev[0] for ev in tracer.snapshot()] == ["boom"]

    def test_concurrent_recording_is_safe(self, tracer):
        """8 threads hammer one ring; every surviving event is intact
        (no torn tuples, no lost-slot crashes)."""
        tracer = TraceRecorder(capacity=64, mode="ring")

        def work(tid):
            for i in range(500):
                tracer.record(f"t{tid}", float(i), float(i) + 1.0,
                              chunk=tid * 1000 + i)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = tracer.snapshot()
        assert len(snap) == 64
        for name, t0, t1, thread, chunk, args in snap:
            assert name == f"t{chunk // 1000}"
            assert t1 == t0 + 1.0


class TestChromeExport:
    def test_golden_file_shape(self, tracer):
        """The export shape is pinned by a golden file: Perfetto and
        chrome://tracing parse this exact structure, so a field rename
        or a ts unit change must fail loudly here."""
        tracer.configure("always")
        tracer.record("decode", 100.0, 100.0015625, chunk=1, rows=512)
        tracer.record("queue_wait", 100.25, 100.5, chunk=1,
                      stage="group")
        tracer.record("apply", 100.5, 100.75, chunk=1, rows=512)
        tracer.record("flush", 101.0, 101.5, chunk=1,
                      table="flows_5m", rows=9)
        got = json.loads(json.dumps(tracer.chrome_trace()))
        for ev in got["traceEvents"]:
            ev["pid"] = 0  # process id is the one run-dependent field
            ev["tid"] = "MainThread"  # pytest's main thread name varies
        with open(GOLDEN) as f:
            want = json.load(f)
        assert got == want

    def test_events_are_complete_spans_in_us(self, tracer):
        tracer.record("s", 2.0, 2.5, chunk=9)
        ev, = tracer.chrome_trace()["traceEvents"]
        assert ev["ph"] == "X"
        assert ev["ts"] == 2.0e6 and ev["dur"] == 0.5e6
        assert ev["args"]["chunk"] == 9

    def test_dump_writes_loadable_json(self, tracer, tmp_path):
        tracer.record("s", 0.0, 1.0)
        path = tracer.dump(str(tmp_path / "trace.json"))
        with open(path) as f:
            doc = json.load(f)
        assert doc["traceEvents"]


class TestDebugTraceEndpoint:
    def test_endpoint_serves_the_flight_recorder(self):
        TRACER.configure("ring")
        with TRACER.span("endpoint_probe", chunk=42):
            pass
        server = MetricsServer(port=0).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/debug/trace") as r:
                assert r.headers["Content-Type"] == "application/json"
                doc = json.load(r)
        finally:
            server.stop()
        probes = [e for e in doc["traceEvents"]
                  if e["name"] == "endpoint_probe"]
        assert probes and probes[0]["args"]["chunk"] == 42

    def test_metrics_endpoint_still_serves(self):
        server = MetricsServer(port=0).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/metrics") as r:
                assert r.status == 200
        finally:
            server.stop()


def _run_traced_worker(sink=None, mode="ring", sinks=None):
    TRACER.configure(mode)
    bus = _stream_to_bus(make_stream())
    worker = StreamWorker(
        Consumer(bus, fixedlen=True),
        make_models(WINDOW, 100),
        sinks if sinks is not None else [sink or CollectSink()],
        WorkerConfig(poll_max=BS, snapshot_every=0,
                     ingest_mode="pipelined"),
    )
    worker.run(stop_when_idle=True)
    return worker


class TestChunkPropagation:
    def test_spans_cross_executor_and_flusher_threads(self):
        """The acceptance shape: one chunk's spans appear on the feed
        (decode), group (prepare), worker (queue_wait + apply) and
        flusher (flush) threads, all carrying the same chunk id."""
        try:
            _run_traced_worker()
            events = TRACER.chrome_trace()["traceEvents"]
        finally:
            TRACER.configure("off")
        by_chunk: dict = {}
        for ev in events:
            chunk = ev.get("args", {}).get("chunk")
            if chunk is not None and chunk >= 0:
                by_chunk.setdefault(chunk, []).append(ev)
        assert by_chunk, "no chunk-tagged spans recorded"
        # at least one chunk shows the full pipelined life cycle
        full = [
            c for c, evs in by_chunk.items()
            if {"decode", "prepare", "queue_wait", "apply"}
            <= {e["name"] for e in evs}
        ]
        assert full, f"no chunk with all stages: {sorted(by_chunk)[:5]}"
        evs = by_chunk[full[0]]
        tids = {e["name"]: e["tid"] for e in evs}
        # decode on the prefetch feed thread, prepare on the ingest
        # group thread, apply on the worker thread — three boundaries
        assert tids["decode"] != tids["apply"]
        assert tids["prepare"] != tids["apply"]
        assert tids["decode"] != tids["prepare"]
        # flush jobs run on the flusher thread, still chunk-tagged
        flushes = [e for e in events
                   if e["name"] == "flush"
                   and e.get("args", {}).get("chunk", -1) >= 0]
        assert flushes
        assert any(e["tid"].startswith("ingest-flush") for e in flushes)

    def test_decode_mints_monotonic_chunk_ids(self):
        bus = _stream_to_bus(make_stream())
        consumer = Consumer(bus, fixedlen=True)
        ids = []
        while True:
            b = consumer.poll(BS)
            if b is None:
                break
            ids.append(b.chunk_id)
        assert len(ids) >= 2
        assert all(i > 0 for i in ids)
        assert ids == sorted(ids) and len(set(ids)) == len(ids)

    def test_worker_error_dumps_flight_recorder(self, monkeypatch,
                                                tmp_path):
        """A crashing worker leaves the post-mortem trace behind — and
        the original exception still propagates."""
        monkeypatch.setenv("TMPDIR", str(tmp_path))
        import tempfile

        tempfile.tempdir = None  # re-read TMPDIR
        try:
            class PoisonSink:
                def write(self, table, rows):
                    raise IOError("sink down")

            from flow_pipeline_tpu.ingest import FlushError

            with pytest.raises(FlushError):
                _run_traced_worker(sink=PoisonSink())
            dumps = list(tmp_path.glob("flowtrace-worker-*.json"))
            assert len(dumps) == 1
            with open(dumps[0]) as f:
                doc = json.load(f)
            assert any(ev.get("args", {}).get("chunk", -1) >= 0
                       for ev in doc["traceEvents"])
        finally:
            tempfile.tempdir = None
            TRACER.configure("off")

    def test_trace_off_worker_parity(self):
        """Recording must be purely observational: off vs ring workers
        land identical sink rows on the same stream."""
        from test_fused import canon_rows

        a, b = CollectSink(), CollectSink()
        _run_traced_worker(sink=a, mode="off")
        _run_traced_worker(sink=b, mode="ring")
        TRACER.configure("off")
        assert set(a.rows) == set(b.rows)
        f5_a = sorted(sum([canon_rows(r) for r in a.rows["flows_5m"]], []))
        f5_b = sorted(sum([canon_rows(r) for r in b.rows["flows_5m"]], []))
        assert f5_a == f5_b


class TestWatermark:
    def test_forced_flush_of_open_window_clamps_to_now(self):
        """A forced flush (shutdown) pops the still-OPEN window, whose
        end lies in the future: the watermark must clamp to wall clock
        (never claim coverage ahead of time) and the latency histogram
        must not take negative observations."""
        import time as _time

        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile

        TRACER.configure("off")
        gen = FlowGenerator(ZipfProfile(n_keys=50, alpha=1.2), seed=3)
        b = gen.batch(BS)
        future = int(_time.time()) + 10_000
        b.columns["time_received"] = np.full(BS, future, np.uint64)
        worker = StreamWorker(
            Consumer(_stream_to_bus([b]), fixedlen=True),
            make_models(WINDOW, 50), [CollectSink()],
            WorkerConfig(poll_max=BS, snapshot_every=0))
        worker.run(stop_when_idle=True)  # finalize force-flushes
        wm = worker.m_commit_wm.value()
        assert 0 < wm <= _time.time()
        count, total = worker.m_commit_lat.value(table="flows_5m")
        assert count >= 1 and total >= 0.0

    def test_commit_watermark_and_latency(self):
        worker = _run_traced_worker(mode="off")
        # every window in the stream is closed + flushed at finalize;
        # the watermark is the newest window END committed to sinks
        wm = worker.m_commit_wm.value()
        assert wm > 0 and wm % WINDOW == 0
        count, total = worker.m_commit_lat.value(table="flows_5m")
        assert count >= 1
        rendered = worker.m_commit_lat.render()
        assert 'le="+Inf"' in rendered
        assert "flow_sink_commit_latency_seconds_bucket" in rendered


class TestHistogram:
    def test_cumulative_buckets_and_inf(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_us", "x", buckets=(10.0, 100.0, 1000.0))
        for v in (5, 10, 50, 5000):
            h.observe(float(v))
        text = h.render()
        assert 'lat_us_bucket{le="10"} 2' in text       # 5, 10 (le is <=)
        assert 'lat_us_bucket{le="100"} 3' in text
        assert 'lat_us_bucket{le="1000"} 3' in text
        assert 'lat_us_bucket{le="+Inf"} 4' in text
        assert "lat_us_sum 5065.0" in text
        assert "lat_us_count 4" in text

    def test_aggregable_across_instances(self):
        """The reason Histogram exists next to Summary: summing bucket
        counters across two 'instances' gives the honest fleet
        distribution (quantiles of summaries cannot be summed)."""
        reg = MetricsRegistry()
        h1 = reg.histogram("a_us", "x", buckets=(10.0, 100.0))
        h2 = reg.histogram("b_us", "x", buckets=(10.0, 100.0))
        for v in (5, 50):
            h1.observe(float(v))
        for v in (50, 500):
            h2.observe(float(v))
        c1, s1 = h1.value()
        c2, s2 = h2.value()
        assert c1 + c2 == 4 and s1 + s2 == 605.0

    def test_label_cardinality_capped(self):
        reg = MetricsRegistry()
        h = reg.histogram("c_us", "x", buckets=(10.0,), max_label_sets=4)
        for i in range(50):
            h.observe(1.0, stage=f"s{i}")
        text = h.render()
        assert text.count("_count{") <= 5  # 4 real + _other
        assert 'stage="_other"' in text

    def test_type_conflict_raises(self):
        reg = MetricsRegistry()
        reg.histogram("m", "x")
        with pytest.raises(TypeError):
            reg.counter("m")


class TestStageTimerCap:
    def test_summary_family_is_capped(self):
        """Satellite: dynamically named stages must not grow the metric
        family unbounded — the tail folds into the overflow stage."""
        reg_before = set(REGISTRY._metrics)
        st = StageTimer()
        for i in range(MAX_STAGES + 50):
            st.observe(f"dyn_stage_{i}", 1.0)
        new = {n for n in REGISTRY._metrics if n not in reg_before
               and n.startswith("flow_summary_dyn_stage_")}
        assert len(new) == MAX_STAGES
        # the 50 overflowed observations all landed in the bounded
        # overflow series, not in 50 new families
        other = REGISTRY._metrics["flow_summary_other_time_us"]
        assert other._count >= 50

    def test_known_stages_unaffected_by_cap(self):
        st = StageTimer()
        st.observe("host_fused", 2.0)
        for i in range(MAX_STAGES + 10):
            st.observe(f"cap_probe_{i}", 1.0)
        st.observe("host_fused", 3.0)  # existing name: never folded
        s = REGISTRY._metrics["flow_summary_host_fused_time_us"]
        assert s._count >= 2

    def test_stage_histogram_records(self):
        st = StageTimer()
        h = REGISTRY._metrics["flow_stage_duration_us"]
        # the shared histogram may have hit ITS label cap from the
        # cap-probe stages above — count both the real and folded series
        def seen():
            return (h.value(stage="host_fused")[0]
                    + h.value(stage="_other")[0])

        before = seen()
        st.observe("host_fused", 1500.0)
        assert seen() == before + 1


class TestConcurrentScrape:
    def test_render_under_concurrent_mutation(self):
        """Satellite: 8 writer threads hammer counters/summaries/
        histograms while the HTTP endpoint is scraped — every response
        parses, no exceptions, final totals exact."""
        reg = MetricsRegistry()
        server = MetricsServer(port=0, registry=reg).start()
        c = reg.counter("scrape_total", "x")
        s = reg.summary("scrape_lat_us", "x")
        h = reg.histogram("scrape_hist_us", "x", buckets=(10.0, 100.0))
        errors = []

        def writer(tid):
            try:
                for i in range(2000):
                    c.inc(1, worker=str(tid))
                    s.observe(float(i % 100), worker=str(tid))
                    h.observe(float(i % 200), worker=str(tid))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        bodies = []
        url = f"http://127.0.0.1:{server.port}/metrics"
        try:
            while any(t.is_alive() for t in threads):
                with urllib.request.urlopen(url) as r:
                    bodies.append(r.read().decode())
            for t in threads:
                t.join()
            with urllib.request.urlopen(url) as r:
                final = r.read().decode()
        finally:
            server.stop()
        assert not errors
        assert len(bodies) >= 1
        for body in bodies + [final]:
            for line in body.splitlines():
                assert line.startswith("#") or " " in line
        # totals exact after the dust settles: 8 threads x 2000
        total = sum(float(line.rsplit(" ", 1)[1])
                    for line in final.splitlines()
                    if line.startswith("scrape_total{"))
        assert total == 16000.0


HAVE_SKETCH = native.sketch_available()
HAVE_FUSED = native.fused_available()


@pytest.mark.skipif(not native.group_available(),
                    reason="libflowdecode.so not built")
class TestNativeStats:
    """The stats out-struct must be purely observational (bit-exact
    outputs with and without it) and its counters sane."""

    def test_hash_group_parity_and_counts(self, rng):
        lanes = rng.integers(0, 64, size=(20000, 4)).astype(np.uint32)
        p1, s1, c1 = native.hash_group(lanes)
        stats = native.new_stats()
        p2, s2, c2 = native.hash_group(lanes, stats=stats)
        np.testing.assert_array_equal(p1, p2)
        np.testing.assert_array_equal(s1, s2)
        assert c1 == c2
        assert stats[native.FF_STAT_ROWS] == 20000
        assert stats[native.FF_STAT_GROUPS] == len(s1)
        assert stats[native.FF_STAT_RADIX_PASSES] == 4
        assert stats[native.FF_STAT_SLOTS["radix"]] > 0
        assert all(int(v) >= 0 for v in stats)

    def test_group_sum_parity_and_fold_time(self, rng):
        lanes = rng.integers(0, 50, size=(10000, 3)).astype(np.uint32)
        vals = rng.integers(0, 1000, size=(10000, 2)).astype(np.uint64)
        r1 = native.group_sum(lanes, vals)
        stats = native.new_stats()
        r2 = native.group_sum(lanes, vals, stats=stats)
        for a, b in zip(r1, r2):
            np.testing.assert_array_equal(a, b)
        assert stats[native.FF_STAT_SLOTS["fold"]] > 0

    @pytest.mark.skipif(not HAVE_SKETCH, reason="no hostsketch engine")
    def test_sketch_kernels_parity_with_stats(self, rng):
        depth, width, planes = 4, 1 << 10, 3
        keys = rng.integers(0, 500, size=(600, 2)).astype(np.uint32)
        vals = rng.integers(1, 100, size=(600, planes)).astype(np.float32)
        cms_a = np.zeros((planes, depth, width), np.uint64)
        cms_b = np.zeros((planes, depth, width), np.uint64)
        stats = native.new_stats()
        native.hs_cms_update(cms_a, keys, vals, None, True, 1)
        native.hs_cms_update(cms_b, keys, vals, None, True, 1,
                             stats=stats)
        np.testing.assert_array_equal(cms_a, cms_b)
        assert stats[native.FF_STAT_SLOTS["cms"]] > 0
        q1 = native.hs_cms_query(cms_a, keys)
        q2 = native.hs_cms_query(cms_b, keys, stats=stats)
        np.testing.assert_array_equal(q1, q2)
        assert stats[native.FF_STAT_SLOTS["topk"]] > 0

    @pytest.mark.skipif(not HAVE_FUSED, reason="no fused dataplane")
    def test_fused_update_parity_with_stats(self, rng):
        """The whole-tree pass with a stats buffer produces bit-identical
        sketch state AND accumulates every phase it executed."""
        from flow_pipeline_tpu.hostsketch.state import host_hh_init
        from flow_pipeline_tpu.models.heavy_hitter import (
            HeavyHitterConfig,
        )

        cfg_root = HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr"), batch_size=4096,
            width=1 << 10, capacity=64)
        cfg_child = HeavyHitterConfig(
            key_cols=("src_addr",), batch_size=4096,
            width=1 << 10, capacity=64)
        plan = native.FusedPlan(
            parent=np.asarray([-1, 0], np.int64),
            sel=np.asarray([0, 1, 2, 3], np.int64),
            sel_off=np.asarray([0, 0, 4], np.int64),
            depth=np.asarray([4, 4], np.int64),
            width=np.asarray([1 << 10, 1 << 10], np.int64),
            cap=np.asarray([64, 64], np.int64),
            conservative=np.asarray([1, 1], np.uint8),
            prefilter=np.asarray([1, 1], np.uint8),
            admission_plain=np.asarray([0, 0], np.uint8),
        )
        lanes = rng.integers(0, 200, size=(4096, 8)).astype(np.uint32)
        vals = rng.integers(1, 1500, size=(4096, 2)).astype(np.float32)
        sa = [host_hh_init(cfg_root), host_hh_init(cfg_child)]
        sb = [host_hh_init(cfg_root), host_hh_init(cfg_child)]
        native.fused_update(lanes, vals, plan, sa, do_sketch=True)
        stats = native.new_stats()
        native.fused_update(lanes, vals, plan, sb, do_sketch=True,
                            stats=stats)
        for a, b in zip(sa, sb):
            np.testing.assert_array_equal(a.cms, b.cms)
            np.testing.assert_array_equal(a.table_keys, b.table_keys)
            np.testing.assert_array_equal(a.table_vals, b.table_vals)
        assert stats[native.FF_STAT_ROWS] == 4096
        for phase in ("radix", "refine", "regroup", "fold", "cms",
                      "topk"):
            assert stats[native.FF_STAT_SLOTS[phase]] > 0, phase


class TestTraceFlag:
    def test_cli_flag_validation(self):
        from flow_pipeline_tpu.cli import main

        rc = main(["processor", "-obs.trace", "sometimes", "-in",
                   "/nonexistent"])
        assert rc == 2

    def test_env_default(self, monkeypatch):
        monkeypatch.setenv("FLOWTPU_TRACE", "always")
        t = TraceRecorder(capacity=4)
        assert t.mode == "always"
