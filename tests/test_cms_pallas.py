"""Pallas CMS kernel correctness in interpret mode (CPU) against an exact
numpy scatter using the same bucket scheme. On real TPU hardware the same
kernel runs compiled; bench.py can compare it with the XLA scatter path."""

import jax.numpy as jnp
import numpy as np
import pytest

from flow_pipeline_tpu.ops.cms import cms_init
from flow_pipeline_tpu.ops.cms_pallas import (
    cms_add_pallas,
    cms_buckets_mixed,
    cms_query_mixed,
)


def np_reference(counts, keys, values, valid):
    p, d, w = counts.shape
    buckets = np.asarray(cms_buckets_mixed(jnp.asarray(keys), d, w))
    out = np.asarray(counts).copy()
    for i in range(len(keys)):
        if not valid[i]:
            continue
        for di in range(d):
            out[:, di, buckets[di, i]] += values[i]
    return out


class TestPallasCMS:
    @pytest.mark.parametrize("n,planes,depth,width,tile",
                             [(64, 1, 2, 256, 128), (128, 3, 4, 512, 128)])
    def test_matches_numpy_scatter(self, rng, n, planes, depth, width, tile):
        keys = rng.integers(0, 2**32, size=(n, 2), dtype=np.uint32).astype(np.int64)
        values = rng.integers(1, 100, size=(n, planes)).astype(np.float32)
        valid = rng.random(n) > 0.2
        counts = cms_init(planes, depth, width)
        got = cms_add_pallas(counts, jnp.asarray(keys), jnp.asarray(values),
                             jnp.asarray(valid), tile=tile, interpret=True)
        want = np_reference(counts, keys, values, valid)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)

    def test_accumulates_across_calls(self, rng):
        keys = rng.integers(0, 2**32, size=(32, 1), dtype=np.uint32).astype(np.int64)
        values = np.ones((32, 1), np.float32)
        valid = np.ones(32, bool)
        counts = cms_init(1, 2, 256)
        counts = cms_add_pallas(counts, jnp.asarray(keys), jnp.asarray(values),
                                jnp.asarray(valid), tile=128, interpret=True)
        counts = cms_add_pallas(counts, jnp.asarray(keys), jnp.asarray(values),
                                jnp.asarray(valid), tile=128, interpret=True)
        est = np.asarray(cms_query_mixed(counts, jnp.asarray(keys)))
        assert (est[:, 0] >= 2).all()  # each key seen twice

    def test_query_upper_bound(self, rng):
        n = 200
        keys = rng.integers(0, 2**32, size=(n, 2), dtype=np.uint32).astype(np.int64)
        values = rng.integers(1, 50, size=(n, 1)).astype(np.float32)
        valid = np.ones(n, bool)
        counts = cms_add_pallas(cms_init(1, 4, 512), jnp.asarray(keys),
                                jnp.asarray(values), jnp.asarray(valid),
                                tile=128, interpret=True)
        est = np.asarray(cms_query_mixed(counts, jnp.asarray(keys)))[:, 0]
        assert (est >= values[:, 0] - 1e-3).all()

    def test_width_not_multiple_of_tile_rejected(self):
        with pytest.raises(ValueError, match="multiple of tile"):
            cms_add_pallas(cms_init(1, 2, 200), jnp.zeros((8, 1), jnp.int32),
                           jnp.ones((8, 1)), tile=128, interpret=True)
