"""Pallas CMS kernel correctness in interpret mode (CPU).

The strongest property: both kernels are exact drop-ins for their XLA
twins on the SAME sketch state — identical bucket scheme (ops.cms), so
linear/conservative updates must match cms_add / cms_add_conservative
cell-for-cell, and ops.cms.cms_query serves either path. On TPU the same
kernels run compiled; bench.py cms compares the paths on hardware.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from flow_pipeline_tpu.ops.cms import (
    cms_add,
    cms_add_conservative,
    cms_init,
    cms_query,
)
from flow_pipeline_tpu.ops.cms_pallas import (
    cms_add_conservative_pallas,
    cms_add_pallas,
)


def make_inputs(rng, n, planes, key_lanes=2):
    # random 64-bit-lane keys are unique w.h.p. — the conservative
    # kernels' contract (callers sort_groupby first)
    keys = rng.integers(0, 2**32, size=(n, key_lanes), dtype=np.uint32)
    values = rng.integers(1, 100, size=(n, planes)).astype(np.float32)
    valid = rng.random(n) > 0.2
    return (jnp.asarray(keys.astype(np.int64)), jnp.asarray(values),
            jnp.asarray(valid))


class TestLinearKernel:
    @pytest.mark.parametrize("n,planes,depth,width,tile",
                             [(64, 1, 2, 256, 128), (128, 3, 4, 512, 128)])
    def test_matches_xla_cms_add(self, rng, n, planes, depth, width, tile):
        keys, values, valid = make_inputs(rng, n, planes)
        counts = cms_init(planes, depth, width)
        got = cms_add_pallas(counts, keys, values, valid, tile=tile,
                             interpret=True)
        want = cms_add(counts, keys, values, valid)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)

    def test_accumulates_and_queries_via_shared_scheme(self, rng):
        keys, values, valid = make_inputs(rng, 32, 1)
        values = jnp.ones_like(values)
        counts = cms_init(1, 2, 256)
        counts = cms_add_pallas(counts, keys, values, valid, tile=128,
                                interpret=True)
        counts = cms_add_pallas(counts, keys, values, valid, tile=128,
                                interpret=True)
        est = np.asarray(cms_query(counts, keys))  # the ops.cms query
        assert (est[np.asarray(valid), 0] >= 2).all()

    def test_mixed_xla_pallas_calls_share_state(self, rng):
        # a sketch updated by the XLA path then the Pallas path must equal
        # one updated twice by either — the drop-in claim, end to end
        keys, values, valid = make_inputs(rng, 64, 2)
        counts = cms_init(2, 3, 384)
        mixed = cms_add(counts, keys, values, valid)
        mixed = cms_add_pallas(mixed, keys, values, valid, tile=128,
                               interpret=True)
        pure = cms_add(cms_add(counts, keys, values, valid),
                       keys, values, valid)
        np.testing.assert_allclose(np.asarray(mixed), np.asarray(pure),
                                   rtol=1e-6)

    def test_width_not_multiple_of_tile_rejected(self):
        with pytest.raises(ValueError, match="multiple of tile"):
            cms_add_pallas(cms_init(1, 2, 200), jnp.zeros((8, 1), jnp.int32),
                           jnp.ones((8, 1)), tile=128, interpret=True)


class TestConservativeKernel:
    @pytest.mark.parametrize("n,planes,depth,width,tile,chunk",
                             [(64, 1, 2, 256, 128, 32),
                              (128, 3, 4, 512, 128, 64)])
    def test_matches_xla_conservative(self, rng, n, planes, depth, width,
                                      tile, chunk):
        keys, values, valid = make_inputs(rng, n, planes)
        counts = cms_init(planes, depth, width)
        # several rounds so estimates feed back into ceilings
        got = counts
        want = counts
        for _ in range(3):
            got = cms_add_conservative_pallas(got, keys, values, valid,
                                              tile=tile, chunk=chunk,
                                              interpret=True)
            want = cms_add_conservative(want, keys, values, valid)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)

    def test_tighter_than_linear(self, rng):
        # the whole point of CU: estimates at most the linear path's
        keys, values, valid = make_inputs(rng, 256, 1)
        lin = cms_init(1, 2, 128)  # narrow -> many collisions
        cu = cms_init(1, 2, 128)
        for _ in range(2):
            lin = cms_add_pallas(lin, keys, values, valid, tile=128,
                                 interpret=True)
            cu = cms_add_conservative_pallas(cu, keys, values, valid,
                                             tile=128, chunk=64,
                                             interpret=True)
        e_lin = np.asarray(cms_query(lin, keys))
        e_cu = np.asarray(cms_query(cu, keys))
        v = np.asarray(valid)
        assert (e_cu[v] <= e_lin[v] + 1e-3).all()
        assert e_cu[v].sum() < e_lin[v].sum()  # strictly tighter somewhere

    def test_invalid_rows_raise_nothing(self, rng):
        keys, values, _ = make_inputs(rng, 64, 1)
        counts = cms_add_conservative_pallas(
            cms_init(1, 2, 256), keys, values, jnp.zeros(64, bool),
            tile=128, chunk=32, interpret=True,
        )
        assert float(jnp.sum(counts)) == 0.0

    def test_still_an_upper_bound(self, rng):
        keys, values, valid = make_inputs(rng, 200, 1)
        counts = cms_add_conservative_pallas(
            cms_init(1, 4, 512), keys, values, valid,
            tile=128, chunk=40, interpret=True,
        )
        est = np.asarray(cms_query(counts, keys))[:, 0]
        v = np.asarray(valid)
        assert (est[v] >= np.asarray(values)[v, 0] - 1e-3).all()

    def test_rows_not_multiple_of_chunk_padded(self, rng):
        # the kernel pads the streamed dimension with inert rows, so any
        # batch size works at full chunk width — and matches the XLA path
        keys, values, valid = make_inputs(rng, 50, 1)
        got = cms_add_conservative_pallas(
            cms_init(1, 2, 256), keys, values, valid,
            tile=128, chunk=64, interpret=True,
        )
        want = cms_add_conservative(cms_init(1, 2, 256), keys, values, valid)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)


class TestModelDispatch:
    def test_hh_model_same_topk_under_either_impl(self):
        # the full flagship step (sort_groupby -> CU cms -> topk) must give
        # identical answers whichever CMS impl the config selects
        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
        from flow_pipeline_tpu.models.heavy_hitter import (
            HeavyHitterConfig,
            HeavyHitterModel,
            hh_estimates,
        )

        batches = [
            FlowGenerator(ZipfProfile(n_keys=60, alpha=1.4), seed=9).batch(1024)
            for _ in range(2)
        ]
        tops, ests = [], []
        for impl in ("xla", "pallas"):
            cfg = HeavyHitterConfig(batch_size=512, width=1 << 10,
                                    capacity=64, cms_impl=impl)
            m = HeavyHitterModel(cfg)
            for b in batches:
                m.update(b)
            tops.append(m.top(10))
            ests.append(np.asarray(hh_estimates(m.state, config=cfg)))
        for k in tops[0]:
            np.testing.assert_array_equal(tops[0][k], tops[1][k])
        np.testing.assert_allclose(ests[0], ests[1], rtol=1e-6)

    def test_unknown_impl_rejected(self):
        from flow_pipeline_tpu.models.heavy_hitter import (
            HeavyHitterConfig,
            HeavyHitterModel,
        )

        m = HeavyHitterModel(HeavyHitterConfig(batch_size=512,
                                               cms_impl="cuda"))
        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile

        with pytest.raises(ValueError, match="unknown cms_impl"):
            m.update(FlowGenerator(ZipfProfile(), seed=1).batch(256))

    def test_awkward_batch_and_width_still_work(self):
        # tile/chunk derive from the config: any width%128==0 and any
        # batch size legal for the xla impl must work under pallas too
        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
        from flow_pipeline_tpu.models.heavy_hitter import (
            HeavyHitterConfig,
            HeavyHitterModel,
        )

        cfg = HeavyHitterConfig(batch_size=1000, width=1920, capacity=32,
                                cms_impl="pallas")
        m = HeavyHitterModel(cfg)
        m.update(FlowGenerator(ZipfProfile(n_keys=30), seed=3).batch(1500))
        top = m.top(5)
        assert top["valid"].any()
