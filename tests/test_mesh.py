"""flowmesh: merge codec, monoid merges, coordinator protocol, and the
N-worker mesh's oracle-exactness — parity (N in {1, 2, 4}), worker
churn (kill one mid-stream: no loss, no double count), and the
mesh-aware /topk fan-out. `make mesh-parity` runs this file."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from flow_pipeline_tpu.cli import (_build_models, _common_flags,
                                   _gen_flags, _processor_flags)
from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.mesh import (InProcessMesh, MeshCoordinator,
                                    MeshCoordinatorServer, ModelSpec,
                                    produce_sharded, shard_ids,
                                    spec_from_models)
from flow_pipeline_tpu.mesh import codec
from flow_pipeline_tpu.mesh import merge as merge_ops
from flow_pipeline_tpu.models.heavy_hitter import (HeavyHitterConfig,
                                                   hh_init)
from flow_pipeline_tpu.models.oracle import exact_groupby
from flow_pipeline_tpu.models.window_agg import WindowAggConfig
from flow_pipeline_tpu.transport import Consumer, InProcessBus
from flow_pipeline_tpu.utils.flags import KNOWN_FLAGS, FlagSet

N_KEYS = 200  # << capacity: admission is collision-free, tables exact
N_FLOWS = 24_000
PARTITIONS = 8
BATCH = 4096

TOP_COLS = ("src_addr", "dst_addr", "src_port", "dst_port", "proto",
            "bytes", "packets", "count", "timeslot")


def _vals(*extra):
    fs = _processor_flags(_gen_flags(_common_flags(FlagSet("test"))))
    return fs.parse([
        "-produce.profile", "zipf", "-zipf.keys", str(N_KEYS),
        "-model.ports=false", "-model.ddos=false", "-model.ips=false",
        "-processor.batch", str(BATCH), "-sketch.capacity", "512",
        *extra,
    ])


def _stream_batches(n_flows=N_FLOWS, seed=0):
    gen = FlowGenerator(ZipfProfile(n_keys=N_KEYS, alpha=1.2), seed=seed,
                        rate=100_000.0)
    out, done = [], 0
    while done < n_flows:
        n = min(8192, n_flows - done)
        out.append(gen.batch(n))
        done += n
    return out


def _make_bus(n_flows=N_FLOWS, partitions=PARTITIONS):
    bus = InProcessBus()
    bus.create_topic("flows", partitions)
    for batch in _stream_batches(n_flows):
        produce_sharded(bus, "flows", batch, partitions)
    return bus


class ListSink:
    def __init__(self):
        self.tables = {}

    def write(self, table, rows):
        self.tables.setdefault(table, []).append(rows)


def _fold_flows5m(tables):
    """Partial flows_5m rows -> {(timeslot, src_as, dst_as, etype):
    (bytes, packets, count)} — the merging-sink semantics."""
    acc = {}
    for rows in tables.get("flows_5m", []):
        for i in range(len(rows["timeslot"])):
            key = (int(rows["timeslot"][i]), int(rows["src_as"][i]),
                   int(rows["dst_as"][i]), int(rows["etype"][i]))
            v = acc.setdefault(key, np.zeros(3, np.uint64))
            v += np.array([rows["bytes"][i], rows["packets"][i],
                           rows["count"][i]], np.uint64)
    return acc


def _oracle_flows5m():
    from flow_pipeline_tpu.schema.batch import FlowBatch

    full = FlowBatch.concat(_stream_batches())
    o = exact_groupby(full, ["src_as", "dst_as", "etype"],
                      ["bytes", "packets"])
    return {
        (int(o["timeslot"][i]), int(o["src_as"][i]), int(o["dst_as"][i]),
         int(o["etype"][i])):
        np.array([o["bytes"][i], o["packets"][i], o["count"][i]],
                 np.uint64)
        for i in range(len(o["timeslot"]))
    }


def _run_single_worker(vals, sink):
    worker = StreamWorker(
        Consumer(_make_bus(), "flows", fixedlen=True),
        _build_models(vals), [sink],
        WorkerConfig(poll_max=BATCH, snapshot_every=0,
                     sketch_backend=vals["sketch.backend"]))
    worker.run(stop_when_idle=True)
    return worker


def _run_mesh(vals, n_workers, sink, **mesh_kw):
    mesh = InProcessMesh(
        _make_bus(), "flows", n_workers,
        model_factory=lambda: _build_models(vals),
        config=WorkerConfig(poll_max=BATCH, snapshot_every=0,
                            sketch_backend=vals["sketch.backend"]),
        sinks=[sink], **mesh_kw)
    mesh.run()
    return mesh


def _assert_topk_equal(r1, r2):
    v1, v2 = np.asarray(r1["valid"]), np.asarray(r2["valid"])
    assert int(v1.sum()) == int(v2.sum())
    for col in TOP_COLS:
        a, b = np.asarray(r1[col])[v1], np.asarray(r2[col])[v2]
        assert a.shape == b.shape and (a == b).all(), col
    # est columns are CMS upper bounds in both legs; the merged sum-of-
    # sketches bound must still dominate the exact table values
    for col in ("bytes", "count"):
        est = np.asarray(r2[f"{col}_est"])[v2].astype(np.float64)
        val = np.asarray(r2[col])[v2].astype(np.float64)
        assert (est >= val - 1e-3).all()


# ---------------------------------------------------------------------------
# merge codec
# ---------------------------------------------------------------------------


class TestCodec:
    def test_uint64_envelope_extremes(self):
        arr = np.array([0, 1, 2**24, 2**53 + 1, 2**63, 2**64 - 1],
                       np.uint64)
        out = codec.decode(codec.encode({"a": arr}))["a"]
        assert out.dtype == np.uint64
        assert (out == arr).all()

    def test_bad_magic_rejected(self):
        with pytest.raises(ValueError, match="magic"):
            codec.decode(b"not a payload")

    def test_hh_state_round_trip_bit_exact(self):
        cfg = HeavyHitterConfig(width=1024, capacity=32, batch_size=256)
        state = hh_init(cfg)
        payload = codec.hh_payload(state)
        out = codec.decode(codec.encode(payload))
        for field in ("cms", "table_keys", "table_vals"):
            a, b = payload[field], out[field]
            assert a.dtype == b.dtype and a.shape == b.shape
            assert (a == b).all()
        assert out["cms"].dtype == np.uint64

    def test_hostsketch_state_round_trip(self):
        from flow_pipeline_tpu.hostsketch.state import host_hh_init

        cfg = HeavyHitterConfig(width=512, capacity=16, batch_size=256)
        st = host_hh_init(cfg)
        st.cms[:] = np.uint64(2**40 + 7)
        st.table_vals[:] = np.float32(3.25)
        payload = codec.hh_payload(st)
        out = codec.decode(codec.encode(payload))
        assert (out["cms"] == st.cms).all()
        assert (out["table_vals"] == st.table_vals).all()
        assert (out["table_keys"] == st.table_keys).all()

    def test_wagg_store_round_trip(self):
        store = {(1, 2, 3, 7): np.array([10, 20, 5], np.uint64),
                 (9, 9, 9, 1): np.array([2**63, 1, 1], np.uint64)}
        payload = codec.wagg_payload(store)
        out = codec.decode(codec.encode(payload))
        merged = merge_ops.merge_wagg([out])
        assert set(merged) == set(store)
        for k in store:
            assert (merged[k] == store[k]).all()

    def test_contribution_structure_round_trip(self):
        payload = {"member": "w0", "ranges": {3: [5, 17]},
                   "watermark": 1_700_000_000, "final": False,
                   "closed": {1200: {"m": {"kind": "dense",
                                           "totals": np.ones((4, 3, 2),
                                                             np.int64)}}}}
        out = codec.decode(codec.encode(payload))
        assert out["member"] == "w0"
        assert out["ranges"][3] == [5, 17]
        assert (out["closed"][1200]["m"]["totals"] == 1).all()

    def test_random_payload_property(self, rng):
        """Random dtype/shape arrays survive the envelope bit-exactly
        (seeded variant; the hypothesis property below runs where
        hypothesis is installed)."""
        for _ in range(25):
            dt = rng.choice([np.uint64, np.uint32, np.float32, np.int64])
            shape = tuple(rng.integers(0, 5, size=rng.integers(1, 4)))
            if dt == np.float32:
                arr = rng.standard_normal(shape).astype(np.float32)
            else:
                arr = rng.integers(0, 2**31, size=shape).astype(dt)
            out = codec.decode(codec.encode({"x": arr}))["x"]
            assert out.dtype == arr.dtype and out.shape == arr.shape
            assert (out == arr).all()


try:  # property test where hypothesis exists (repo convention)
    from hypothesis import given, settings
    from hypothesis import strategies as st

    @settings(max_examples=50, deadline=None)
    @given(st.lists(st.integers(min_value=0, max_value=2**64 - 1),
                    min_size=0, max_size=64))
    def test_codec_u64_property(values):
        arr = np.array(values, dtype=np.uint64)
        out = codec.decode(codec.encode(
            {"a": arr, "meta": {"n": len(values)}}))
        assert out["meta"]["n"] == len(values)
        assert out["a"].dtype == np.uint64
        assert (out["a"] == arr).all()
except ImportError:  # pragma: no cover - env without hypothesis
    pass


# ---------------------------------------------------------------------------
# monoid merges
# ---------------------------------------------------------------------------


class TestMerges:
    def test_wagg_merge_sums_by_key(self):
        a = codec.wagg_payload({(1, 2): np.array([10, 1], np.uint64)})
        b = codec.wagg_payload({(1, 2): np.array([5, 2], np.uint64),
                                (3, 4): np.array([7, 1], np.uint64)})
        merged = merge_ops.merge_wagg([a, b])
        assert (merged[(1, 2)] == np.array([15, 3], np.uint64)).all()
        assert (merged[(3, 4)] == np.array([7, 1], np.uint64)).all()

    def test_cms_merge_is_linear(self, rng):
        """sum of per-shard plain-CMS sketches == CMS of the union
        stream (the linear-sketch property the mesh merge leans on)."""
        from flow_pipeline_tpu.hostsketch.engine import np_cms_update

        cfg = HeavyHitterConfig(width=256, depth=2, capacity=8,
                                conservative=False, batch_size=64)
        keys = rng.integers(0, 50, size=(200, 2)).astype(np.uint32)
        vals = rng.integers(1, 100, size=(200, 3)).astype(np.float32)
        whole = np.zeros((3, cfg.depth, cfg.width), np.uint64)
        np_cms_update(whole, keys, vals, conservative=False)
        shard_of = keys[:, 0] % 2
        parts = []
        for s in (0, 1):
            cms = np.zeros_like(whole)
            sel = shard_of == s
            np_cms_update(cms, keys[sel], vals[sel], conservative=False)
            parts.append(cms)
        assert (parts[0] + parts[1] == whole).all()

    def test_hh_table_merge_disjoint_ranks_and_ties(self):
        cfg = HeavyHitterConfig(key_cols=("proto",),
                                value_cols=("bytes",), width=128,
                                depth=2, capacity=4, batch_size=64)
        empty = codec.hh_payload(hh_init(cfg))

        def table(rows):
            p = {k: v.copy() for k, v in empty.items() if k != "kind"}
            p["kind"] = "hh"
            for i, (key, val) in enumerate(rows):
                p["table_keys"][i] = key
                p["table_vals"][i] = val
            return p

        a = table([((5,), (100.0, 1.0)), ((9,), (50.0, 2.0))])
        b = table([((3,), (100.0, 3.0)), ((7,), (10.0, 4.0))])
        merged = merge_ops.merge_hh([a, b], cfg)
        keys = merged["table_keys"][:, 0].tolist()
        # rank by value desc; the 100.0 tie breaks lexicographically
        assert keys == [3, 5, 9, 7]
        assert merged["table_vals"][0, 0] == 100.0

    def test_hh_merge_sums_duplicate_keys(self):
        """Carry + successor contributions for the SAME key (churn
        mid-window) sum — the table-table fold semantics."""
        cfg = HeavyHitterConfig(key_cols=("proto",),
                                value_cols=("bytes",), width=128,
                                depth=2, capacity=4, batch_size=64)
        base = codec.hh_payload(hh_init(cfg))

        def table(val):
            p = {k: v.copy() for k, v in base.items() if k != "kind"}
            p["kind"] = "hh"
            p["table_keys"][0] = (6,)
            p["table_vals"][0] = (val, 1.0)
            return p

        merged = merge_ops.merge_hh([table(30.0), table(12.0)], cfg)
        assert merged["table_keys"][0, 0] == 6
        assert merged["table_vals"][0, 0] == 42.0

    def test_dense_merge_sums_planes(self):
        a = codec.dense_payload(np.full((8, 3, 2), 3, np.int32))
        b = codec.dense_payload(np.full((8, 3, 2), 4, np.int32))
        assert (merge_ops.merge_dense([a, b]) == 7).all()


# ---------------------------------------------------------------------------
# coordinator protocol units (no jax, synthetic payloads)
# ---------------------------------------------------------------------------


def _wagg_spec():
    cfg = WindowAggConfig(key_cols=("src_as",), value_cols=("bytes",),
                          window_seconds=300, scale_col=None,
                          batch_size=256)
    return ModelSpec("flows_5m", "wagg", cfg, 0, 300)


def _contrib(ranges, wm, closed=None, open_=None, final=False,
             release=False, flows=0):
    return {"ranges": ranges, "watermark": wm, "closed": closed or {},
            "open": open_ or {}, "final": final, "release": release,
            "flows": flows}


def _wagg_win(key, val):
    return {"flows_5m": codec.wagg_payload(
        {(key,): np.array([val, 1], np.uint64)})}


class TestCoordinatorProtocol:
    def make(self, partitions=2, **kw):
        return MeshCoordinator([_wagg_spec()], partitions, **kw)

    def test_join_assign_epoch(self):
        c = self.make()
        assert c.join("a")["epoch"] == 1
        s = c.sync("a")
        assert s["action"] == "run"
        assert sorted(s["assign"]) == [0, 1]
        assert c.join("b")["epoch"] == 2
        assert c.sync("a")["action"] == "resync"

    def test_submit_advances_frontier_and_merges(self):
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        r = c.submit("a", _contrib({0: [0, 10]}, wm=900,
                                   closed={300: _wagg_win(7, 50)}))
        assert r["ok"]
        assert c.status()["covered"] == [10]
        rows = c.merged_rows("flows_5m", 300)
        assert len(rows) == 1
        assert int(rows[0]["bytes"][0]) == 50

    def test_merge_waits_for_every_partition(self):
        c = self.make(partitions=2)
        c.join("a")
        c.join("b")
        sa, sb = c.sync("a"), c.sync("b")
        pa = list(sa["assign"])[0]
        c.submit("a", _contrib({pa: [0, 5]}, wm=900,
                               closed={300: _wagg_win(1, 10)}))
        assert not c.merged_rows("flows_5m", 300)  # b's watermark at 0
        pb = list(sb["assign"])[0]
        c.submit("b", _contrib({pb: [0, 5]}, wm=900,
                               closed={300: _wagg_win(1, 5)}))
        rows = c.merged_rows("flows_5m", 300)
        assert len(rows) == 1
        assert int(rows[0]["bytes"][0]) == 15  # summed across members

    def test_zombie_submission_fenced(self):
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        c.fence("a")
        r = c.submit("a", _contrib({0: [0, 10]}, wm=900))
        assert not r["ok"] and r["reason"] == "fenced"
        assert c.status()["covered"] == [0]  # nothing accepted
        assert c.sync("a")["action"] == "rejoin"

    def test_range_gap_fences(self):
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        r = c.submit("a", _contrib({0: [5, 10]}, wm=0))  # gap: covered=0
        assert not r["ok"]
        assert c.sync("a")["action"] == "rejoin"

    def test_death_promotes_carry_and_successor_resumes(self):
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 8]}, wm=100,
                               open_={300: _wagg_win(2, 30)}))
        c.join("b")
        c.fence("a")
        s = c.sync("b")
        assert s["action"] == "run"
        assert s["assign"] == {0: 8}  # resumes at the carry frontier
        c.submit("b", _contrib({0: [8, 12]}, wm=700,
                               closed={300: _wagg_win(2, 12)},
                               final=True))
        rows = c.merged_rows("flows_5m", 300)
        assert len(rows) == 1
        # carry (30) + successor (12): no loss, no double count
        assert int(rows[0]["bytes"][0]) == 42

    def test_resubmission_replaces_carry(self):
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 4]}, wm=100,
                               open_={300: _wagg_win(2, 10)}))
        # the second submission's open state COVERS the first's rows
        c.submit("a", _contrib({0: [4, 9]}, wm=100,
                               open_={300: _wagg_win(2, 25)}))
        c.fence("a")
        c.join("b")
        c.sync("b")
        c.submit("b", _contrib({0: [9, 9]}, wm=700, final=True))
        rows = c.merged_rows("flows_5m", 300)
        assert int(rows[0]["bytes"][0]) == 25  # replaced, not summed

    def test_heartbeat_expiry_fences(self):
        now = [0.0]
        c = self.make(partitions=1, heartbeat_timeout=1.0,
                      time_fn=lambda: now[0])
        c.join("a")
        c.sync("a")
        now[0] = 10.0
        assert c.expire() == ["a"]
        assert c.sync("a")["action"] == "rejoin"

    def test_late_wagg_contribution_emits_extra_partials(self):
        c = self.make(partitions=1)
        # delta, not absolute: the late counter is process-global
        late0 = c._m["late"].value(model="flows_5m")
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 5]}, wm=900,
                               closed={300: _wagg_win(3, 10)}))
        assert len(c.merged_rows("flows_5m", 300)) == 1
        c.submit("a", _contrib({0: [5, 6]}, wm=901,
                               closed={300: _wagg_win(3, 4)}))
        rows = c.merged_rows("flows_5m", 300)
        assert len(rows) == 2  # late partial emitted, not dropped
        assert c._m["late"].value(model="flows_5m") - late0 == 1.0

    def test_rejoin_fence_completes_barrier_and_emits(self):
        """A crashed member rejoining under its pinned id fences the old
        incarnation; if its promoted carry is the LAST contribution a
        window needed, that window must still be emitted (regression:
        join() discarded the ready-merge list — silent window loss)."""
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 5]}, wm=900,
                               open_={300: _wagg_win(7, 33)}))
        assert not c.merged_rows("flows_5m", 300)  # carried, not pending
        c.join("a")  # restart before expiry: death-then-join
        rows = c.merged_rows("flows_5m", 300)
        assert len(rows) == 1
        assert int(rows[0]["bytes"][0]) == 33

    def test_leave_fence_completes_barrier_and_emits(self):
        """Same loss mode via leave() while owning non-final partitions
        (the fence branch): the promoted carry's merges must emit."""
        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        c.submit("a", _contrib({0: [0, 5]}, wm=900,
                               open_={300: _wagg_win(2, 21)}))
        c.leave("a")
        rows = c.merged_rows("flows_5m", 300)
        assert len(rows) == 1
        assert int(rows[0]["bytes"][0]) == 21

    def test_query_topk_live_carry_not_double_counted(self):
        """A live member's carry is a SUBSET of its provider state; the
        /topk fan-out must count it once (regression: carries were
        folded next to provider states — up to 2x inflation)."""
        cfg = HeavyHitterConfig(key_cols=("proto",),
                                value_cols=("bytes",), width=128,
                                depth=2, capacity=4, batch_size=64)
        spec = ModelSpec("talkers", "hh", cfg, 4, 300)
        c = MeshCoordinator([spec], 1)

        def table(val):
            p = codec.hh_payload(hh_init(cfg))
            p["table_keys"][0] = (6,)
            p["table_vals"][0] = (val, 1.0)
            return p

        provider = lambda model: {"slot": 300, "payload": table(30.0)}
        c.join("a", provider=provider)
        c.sync("a")
        # progress submission: the carry holds an earlier subset (20)
        c.submit("a", _contrib({0: [0, 4]}, wm=100,
                               open_={300: {"talkers": table(20.0)}}))
        out = c.query_topk("talkers")
        assert out["window_start"] == 300
        assert out["rows"][0]["bytes"] == 30.0  # not 50.0

    def test_merged_ledger_retention_bounded(self):
        """The merged-rows ledger keeps only the newest slots per model
        (sinks are the durable home; an endless stream must not grow
        coordinator RAM per window) while late detection keeps working
        for evicted windows."""
        from flow_pipeline_tpu.mesh.coordinator import \
            MERGED_LEDGER_SLOTS

        c = self.make(partitions=1)
        c.join("a")
        c.sync("a")
        n = MERGED_LEDGER_SLOTS + 4
        for i in range(n):
            slot = 300 * (i + 1)
            c.submit("a", _contrib(
                {0: [i, i + 1]}, wm=slot + 600,
                closed={slot: _wagg_win(1, 10)}))
        kept = sorted(s for (name, s) in c.merged if name == "flows_5m")
        assert len(kept) == MERGED_LEDGER_SLOTS
        assert kept[0] == 300 * (n - MERGED_LEDGER_SLOTS + 1)  # oldest gone
        assert not c.merged_rows("flows_5m", 300)  # evicted
        # a late contribution for an EVICTED window still registers late
        late_before = c._m["late"].value(model="flows_5m")
        c.submit("a", _contrib({0: [n, n]}, wm=10**9,
                               closed={300: _wagg_win(1, 4)}))
        assert c._m["late"].value(model="flows_5m") == late_before + 1

    def test_more_members_than_partitions_idles_extra(self):
        c = self.make(partitions=1)
        c.join("a")
        c.join("b")
        acts = {m: c.sync(m)["action"] for m in ("a", "b")}
        assert sorted(acts.values()) == ["run", "run"]
        owned = [len(v["owned"]) for v in c.status()["members"].values()]
        assert sorted(owned) == [0, 1]


# ---------------------------------------------------------------------------
# sharding
# ---------------------------------------------------------------------------


class TestSharding:
    def test_shard_ids_deterministic_and_key_consistent(self):
        batch = _stream_batches(n_flows=8192)[0]
        a = shard_ids(batch, 8)
        b = shard_ids(batch, 8)
        assert (a == b).all()
        # same 5-tuple -> same shard: group rows by key, check constancy
        from flow_pipeline_tpu.engine.hostfused import _key_lanes_np

        lanes = _key_lanes_np(
            batch.columns,
            ("src_addr", "dst_addr", "src_port", "dst_port", "proto"))
        seen = {}
        for i in range(len(batch)):
            key = lanes[i].tobytes()
            assert seen.setdefault(key, a[i]) == a[i]

    def test_produce_sharded_covers_all_rows(self):
        bus = InProcessBus()
        bus.create_topic("flows", 4)
        batch = _stream_batches(n_flows=8192)[0]
        n = produce_sharded(bus, "flows", batch, 4)
        assert n == len(batch)
        total = sum(bus.end_offset("flows", p) for p in range(4))
        assert total == len(batch)


# ---------------------------------------------------------------------------
# end-to-end oracle exactness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_workers", [1, 2, 4])
def test_mesh_parity_vs_single_worker(n_workers):
    """The acceptance gate: an N-worker mesh's merged flows_5m and top-K
    outputs are bit-exact to a single worker consuming the identical
    sharded bus — and flows_5m additionally matches the pure-numpy exact
    oracle over the whole stream."""
    from flow_pipeline_tpu.obs import REGISTRY

    merged_before = REGISTRY.counter(
        "mesh_windows_merged_total").value(model="top_talkers")
    vals = _vals()
    sink1, sink2 = ListSink(), ListSink()
    _run_single_worker(vals, sink1)
    mesh = _run_mesh(vals, n_workers, sink2)
    oracle = _oracle_flows5m()
    for fold in (_fold_flows5m(sink1.tables), _fold_flows5m(sink2.tables)):
        assert set(fold) == set(oracle)
        for k in oracle:
            assert (fold[k] == oracle[k]).all()
    _assert_topk_equal(sink1.tables["top_talkers"][0],
                       sink2.tables["top_talkers"][0])
    # exactly one merged top-K window for THIS mesh (the registry is
    # process-global, so assert the delta)
    assert mesh.coordinator._m["merged"].value(
        model="top_talkers") - merged_before == 1.0


def test_mesh_parity_hostsketch_backend():
    """Members on the host sketch engine (its export seam feeds the
    merge codec) stay oracle-exact through the mesh."""
    vals = _vals("-sketch.backend", "host")
    sink1, sink2 = ListSink(), ListSink()
    _run_single_worker(vals, sink1)
    _run_mesh(vals, 2, sink2)
    _assert_topk_equal(sink1.tables["top_talkers"][0],
                       sink2.tables["top_talkers"][0])
    f1, f2 = _fold_flows5m(sink1.tables), _fold_flows5m(sink2.tables)
    assert set(f1) == set(f2)
    for k in f1:
        assert (f1[k] == f2[k]).all()


def test_mesh_churn_kill_one_worker_stays_exact():
    """The churn acceptance criterion: kill a member mid-stream (abrupt,
    no final submission), fence it, let the rebalanced mesh finish —
    merged flows_5m and top-K stay oracle-exact (no loss, no double
    count). submit_every=2 keeps progress carries flowing so the death
    promotes a real mid-window carry."""
    vals = _vals()
    sink1, sink2 = ListSink(), ListSink()
    _run_single_worker(vals, sink1)
    mesh = InProcessMesh(
        _make_bus(), "flows", 3,
        model_factory=lambda: _build_models(vals),
        config=WorkerConfig(poll_max=BATCH, snapshot_every=0),
        sinks=[sink2], submit_every=2)
    mesh.start()
    victim = mesh.members[1]
    deadline = time.time() + 120
    while time.time() < deadline:
        w = victim.worker
        if w is not None and w.flows_seen >= BATCH:
            break
        time.sleep(0.002)
    else:
        pytest.fail("victim never processed a batch")
    mesh.kill_member(1)
    mesh.wait_idle()
    mesh.finalize()
    oracle = _oracle_flows5m()
    fold = _fold_flows5m(sink2.tables)
    assert set(fold) == set(oracle)
    for k in oracle:
        assert (fold[k] == oracle[k]).all()
    _assert_topk_equal(sink1.tables["top_talkers"][0],
                       sink2.tables["top_talkers"][0])
    assert mesh.coordinator._m["rebalance"].value(reason="death") >= 1.0


def test_mesh_topk_query_equals_single_worker_oracle():
    """Satellite: the coordinator's fanned-out /topk over the merged
    open-window view equals the single-worker answer at the same
    consumed point (everything ingested, window still open)."""
    vals = _vals()
    # single worker: consume everything but do NOT finalize
    worker = StreamWorker(
        Consumer(_make_bus(), "flows", fixedlen=True),
        _build_models(vals), [],
        WorkerConfig(poll_max=BATCH, snapshot_every=0))
    while worker.run_once():
        pass
    with worker.lock:
        worker.sync_sketch_states()
        model = worker.models["top_talkers"]
        single = model.model.top(10)
        single["timeslot"] = np.full(len(single["valid"]),
                                     model.current_slot, np.uint64)
    # mesh: consume everything, query BEFORE finalize
    mesh = InProcessMesh(
        _make_bus(), "flows", 2,
        model_factory=lambda: _build_models(vals),
        config=WorkerConfig(poll_max=BATCH, snapshot_every=0))
    server = MeshCoordinatorServer(mesh.coordinator, port=0).start()
    mesh.start()
    try:
        mesh.wait_idle()
        url = (f"http://127.0.0.1:{server.port}/topk"
               f"?model=top_talkers&k=10")
        remote = json.load(urllib.request.urlopen(url))
        direct = mesh.coordinator.query_topk("top_talkers", 10)
    finally:
        mesh.finalize()
        server.stop()
    assert remote["window_start"] == direct["window_start"] \
        == int(single["timeslot"][0])
    from flow_pipeline_tpu.sink.base import rows_to_records

    single_records = rows_to_records(single)
    for got in (direct["rows"], ):
        assert len(got) == len(single_records)
        for g, s in zip(got, single_records):
            for col in ("src_addr", "dst_addr", "src_port", "dst_port",
                        "proto", "bytes", "packets", "count"):
                assert g[col] == s[col], col
    # the HTTP answer is the same fan-out JSON-encoded
    assert len(remote["rows"]) == len(single_records)
    assert [r["bytes"] for r in remote["rows"]] == \
        [r["bytes"] for r in single_records]


@pytest.mark.parametrize("n_workers", [1, 2, 4])
def test_mesh_parity_invertible_vs_single_worker(n_workers):
    """Invertible-family mesh citizenship (r16 acceptance): an N-worker
    mesh running -hh.sketch=invertible merges by a PLAIN element-wise
    u64 sum (merge_hh_inv — no table folds, no device-rank semantics)
    and its decoded merged output is bit-exact to a single worker
    consuming the identical sharded bus; flows_5m stays oracle-exact."""
    vals = _vals("-sketch.backend", "host", "-hh.sketch", "invertible")
    sink1, sink2 = ListSink(), ListSink()
    _run_single_worker(vals, sink1)
    _run_mesh(vals, n_workers, sink2)
    oracle = _oracle_flows5m()
    for fold in (_fold_flows5m(sink1.tables), _fold_flows5m(sink2.tables)):
        assert set(fold) == set(oracle)
        for k in oracle:
            assert (fold[k] == oracle[k]).all()
    _assert_topk_equal(sink1.tables["top_talkers"][0],
                       sink2.tables["top_talkers"][0])


def test_mesh_churn_invertible_kill_one_worker_stays_exact():
    """Kill-one-worker churn in invertible mode: carry promotion ships
    the dead member's u64 planes, the successor replays the rest, and
    the merged decode stays bit-exact to the single-worker answer."""
    vals = _vals("-sketch.backend", "host", "-hh.sketch", "invertible")
    sink1, sink2 = ListSink(), ListSink()
    _run_single_worker(vals, sink1)
    mesh = InProcessMesh(
        _make_bus(), "flows", 3,
        model_factory=lambda: _build_models(vals),
        config=WorkerConfig(poll_max=BATCH, snapshot_every=0,
                            sketch_backend="host"),
        sinks=[sink2], submit_every=2)
    mesh.start()
    victim = mesh.members[1]
    deadline = time.time() + 120
    while time.time() < deadline:
        w = victim.worker
        if w is not None and w.flows_seen >= BATCH:
            break
        time.sleep(0.002)
    else:
        pytest.fail("victim never processed a batch")
    mesh.kill_member(1)
    mesh.wait_idle()
    mesh.finalize()
    oracle = _oracle_flows5m()
    fold = _fold_flows5m(sink2.tables)
    assert set(fold) == set(oracle)
    for k in oracle:
        assert (fold[k] == oracle[k]).all()
    _assert_topk_equal(sink1.tables["top_talkers"][0],
                       sink2.tables["top_talkers"][0])
    assert mesh.coordinator._m["rebalance"].value(reason="death") >= 1.0


def test_mesh_flags_registered_and_validated():
    for flag in ("mesh.workers", "mesh.role", "mesh.coordinator",
                 "mesh.id", "mesh.listen", "mesh.heartbeat"):
        assert flag in KNOWN_FLAGS
    from flow_pipeline_tpu.cli import processor_main

    with pytest.raises(ValueError, match="mesh.role"):
        processor_main(["-mesh.role", "bogus", "-in", "/nonexistent"])


def test_spec_from_models_skips_ddos():
    vals = _vals("-model.ddos=true")
    specs = spec_from_models(_build_models(vals))
    names = {s.name for s in specs}
    assert "flows_5m" in names and "top_talkers" in names
    assert "ddos_alerts" not in names  # per-shard detection stays local
