"""Property-based fuzzing (hypothesis): the wire codec and the exact
aggregation path must hold for arbitrary well-typed inputs, not just
generator-shaped ones."""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from flow_pipeline_tpu.models.oracle import flows_5m
from flow_pipeline_tpu.models.window_agg import WindowAggConfig, WindowAggregator
from flow_pipeline_tpu.schema import (
    FlowBatch,
    FlowMessage,
    decode_message,
    encode_message,
)

u32 = st.integers(0, 2**32 - 1)
u64 = st.integers(0, 2**64 - 1)
u16 = st.integers(0, 2**16 - 1)
u8 = st.integers(0, 255)
addr = st.binary(min_size=0, max_size=16)

messages = st.builds(
    FlowMessage,
    type=st.integers(0, 4),
    time_received=u64,
    sampling_rate=u64,
    sequence_num=u32,
    time_flow_start=u64,
    time_flow_end=u64,
    src_addr=addr,
    dst_addr=addr,
    sampler_address=addr,
    bytes=u64,
    packets=u64,
    src_as=u32,
    dst_as=u32,
    in_if=u32,
    out_if=u32,
    proto=u8,
    src_port=u16,
    dst_port=u16,
    ip_tos=u8,
    forwarding_status=u8,
    ip_ttl=u8,
    tcp_flags=u8,
    etype=u16,
    icmp_type=u8,
    icmp_code=u8,
    ipv6_flow_label=st.integers(0, 2**20 - 1),
    flow_direction=st.integers(0, 1),
)


class TestWireProperty:
    @given(messages)
    @settings(max_examples=200, deadline=None)
    def test_roundtrip(self, msg):
        assert decode_message(encode_message(msg)) == msg

    @given(st.binary(max_size=64))
    @settings(max_examples=200, deadline=None)
    def test_decoder_never_crashes_unhandled(self, blob):
        # arbitrary bytes either decode or raise ValueError — nothing else
        try:
            decode_message(blob)
        except ValueError:
            pass


class TestWindowAggProperty:
    @given(
        st.lists(
            st.tuples(
                st.integers(1_000_000, 1_000_000 + 1800),  # time_received
                st.integers(64000, 64004),  # src_as
                st.integers(64000, 64004),  # dst_as
                st.sampled_from([0x0800, 0x86DD]),  # etype
                st.integers(0, 65535),  # bytes
                st.integers(0, 100),  # packets
            ),
            min_size=1,
            max_size=300,
        ),
        st.integers(1, 7),  # batch split factor
    )
    @settings(max_examples=30, deadline=None)
    def test_matches_oracle_for_any_stream(self, rows, splits):
        n = len(rows)
        batch = FlowBatch.empty(n)
        c = batch.columns
        for i, (ts, sas, das, et, by, pk) in enumerate(rows):
            c["time_received"][i] = ts
            c["src_as"][i] = sas
            c["dst_as"][i] = das
            c["etype"][i] = et
            c["bytes"][i] = by
            c["packets"][i] = pk
        agg = WindowAggregator(WindowAggConfig(batch_size=64))
        # feed in arbitrary chunk sizes (exercises padding + chunking)
        step = max(1, n // splits)
        for start in range(0, n, step):
            agg.update(batch.slice(start, start + step))
        out = agg.flush(force=True)
        oracle = flows_5m(batch)
        assert len(out["timeslot"]) == len(oracle["timeslot"])
        got = {
            (int(t), int(s), int(d), int(e)): (int(b), int(p), int(cn))
            for t, s, d, e, b, p, cn in zip(
                out["timeslot"], out["src_as"], out["dst_as"], out["etype"],
                out["bytes"], out["packets"], out["count"],
            )
        }
        for i in range(len(oracle["timeslot"])):
            key = (int(oracle["timeslot"][i]), int(oracle["src_as"][i]),
                   int(oracle["dst_as"][i]), int(oracle["etype"][i]))
            assert got[key] == (int(oracle["bytes"][i]),
                                int(oracle["packets"][i]),
                                int(oracle["count"][i]))


class TestCollectorDecodeProperty:
    """The UDP decoders must never raise anything but ValueError/struct
    hygiene regardless of datagram content — one spoofed packet must not
    kill a listener (collector/udp.py catches exactly those)."""

    @given(st.binary(max_size=512))
    @settings(max_examples=300, deadline=None)
    def test_netflow_decoder_contained(self, blob):
        import struct as struct_mod

        from flow_pipeline_tpu.collector import TemplateCache, decode_netflow

        try:
            decode_netflow(blob, TemplateCache())
        except (ValueError, struct_mod.error):
            pass

    @given(st.binary(max_size=512))
    @settings(max_examples=300, deadline=None)
    def test_sflow_decoder_contained(self, blob):
        import struct as struct_mod

        from flow_pipeline_tpu.collector import decode_sflow

        try:
            decode_sflow(blob)
        except (ValueError, struct_mod.error):
            pass

    @given(
        st.lists(st.binary(max_size=80), min_size=0, max_size=4),
        st.booleans(),
        st.integers(0, 3),
    )
    @settings(max_examples=200, deadline=None)
    def test_ipfix_varlen_payloads_decode_or_raise(self, payloads, long_form,
                                                   extra_fixed):
        """Structured fuzz of the RFC 7011 varlen path: ANY payload sizes
        (incl. 3-byte-form lengths and starved fixed tails from mutation)
        either decode to records with the right fixed values or raise
        ValueError — never a crash, never a silent mis-parse."""
        import struct as struct_mod

        from flow_pipeline_tpu.collector import TemplateCache, decode_netflow

        fields = [(1, 4), (371, 0xFFFF)] + [(2, 4)] * extra_fixed
        tmpl_body = struct_mod.pack(">HH", 310, len(fields))
        for t, ln in fields:
            tmpl_body += struct_mod.pack(">HH", t, ln)
        tmpl_set = struct_mod.pack(">HH", 2, 4 + len(tmpl_body)) + tmpl_body
        recs = b""
        for i, payload in enumerate(payloads):
            prefix = (bytes([255]) + struct_mod.pack(">H", len(payload))
                      if long_form else bytes([min(len(payload), 254)]))
            payload = payload[:254] if not long_form else payload
            recs += struct_mod.pack(">I", 100 + i) + prefix + payload
            recs += struct_mod.pack(">I", 10 + i) * extra_fixed
        data_set = struct_mod.pack(">HH", 310, 4 + len(recs)) + recs
        total = 16 + len(tmpl_set) + len(data_set)
        header = struct_mod.pack(">HHIII", 10, total, 1_700_000_000, 1, 5)
        msgs = decode_netflow(header + tmpl_set + data_set, TemplateCache())
        assert [m.bytes for m in msgs] == [100 + i
                                           for i in range(len(payloads))]
        assert all(m.packets == (10 + i if extra_fixed else 0)
                   for i, m in enumerate(msgs))


class TestSpaceSavingAdmission:
    """Adversarial admission at the eviction boundary (VERDICT r5 #5),
    fuzzed: arbitrary candidate streams against a deliberately narrow
    CMS. The bounds and the round driver live in test_models.
    drive_admission_rounds (also exercised there with a fixed seed, for
    environments without hypothesis); hypothesis explores the stream
    space — skewed, bursty, repeat-heavy — looking for a violation of
    the upper-bound / dropped-mass guarantees."""

    @settings(max_examples=10, deadline=None)
    @given(st.lists(
        st.lists(st.tuples(st.integers(1, 1200),
                           st.integers(1, 1000)),
                 min_size=1, max_size=16),
        min_size=3, max_size=8))
    def test_bounds_hold_under_narrow_cms(self, rounds):
        from test_models import drive_admission_rounds

        drive_admission_rounds(
            [[(k, float(v)) for k, v in pairs] for pairs in rounds])


class TestSpreadProperty:
    """flowspread register monoid (ops/spread.py, hostsketch
    np_spread_*): merge is a commutative/associative/idempotent max,
    update order cannot change state, and the decoded estimate is
    monotone as the true distinct set grows — the three facts the
    mesh-exactness argument rests on."""

    regs_arrays = st.integers(0, 2**32 - 1).map(
        lambda seed: np.random.default_rng(seed).integers(
            0, 34, (2, 8, 16), dtype=np.uint8))

    @given(a=regs_arrays, b=regs_arrays, c=regs_arrays)
    @settings(max_examples=60, deadline=None)
    def test_merge_is_a_bounded_semilattice(self, a, b, c):
        m = np.maximum
        assert np.array_equal(m(a, b), m(b, a))
        assert np.array_equal(m(m(a, b), c), m(a, m(b, c)))
        assert np.array_equal(m(a, a), a)
        # saturated planes are absorbing (u8 edge)
        full = np.full_like(a, 255)
        assert np.array_equal(m(a, full), full)

    @given(
        pairs=st.lists(st.tuples(st.integers(0, 40), st.integers(0, 5000)),
                       min_size=1, max_size=200),
        perm_seed=st.integers(0, 2**32 - 1),
        split=st.integers(1, 7),
    )
    @settings(max_examples=40, deadline=None)
    def test_update_order_and_chunking_cannot_change_state(
            self, pairs, perm_seed, split):
        from flow_pipeline_tpu.hostsketch.engine import np_spread_update

        keys = np.array([[k] for k, _ in pairs], np.uint32)
        elems = np.array([[e] for _, e in pairs], np.uint32)
        ref = np.zeros((2, 16, 16), np.uint8)
        np_spread_update(ref, keys, elems)
        order = np.random.default_rng(perm_seed).permutation(len(pairs))
        got = np.zeros((2, 16, 16), np.uint8)
        step = max(1, len(pairs) // split)
        for s in range(0, len(pairs), step):
            sel = order[s:s + step]
            np_spread_update(got, keys[sel], elems[sel])
        assert np.array_equal(ref, got)

    @given(
        n_elems=st.integers(1, 400),
        seed=st.integers(0, 2**32 - 1),
        key=st.integers(0, 2**32 - 1),
    )
    @settings(max_examples=30, deadline=None)
    def test_decoded_spread_monotone_in_true_distinct_count(
            self, n_elems, seed, key):
        from flow_pipeline_tpu.hostsketch.engine import (np_spread_query,
                                                         np_spread_update)

        rng = np.random.default_rng(seed)
        elems = rng.choice(2**32, size=n_elems, replace=False).astype(
            np.uint32).reshape(-1, 1)
        keys = np.full((n_elems, 1), key, np.uint32)
        regs = np.zeros((2, 32, 32), np.uint8)
        qkey = keys[:1]
        prev = np_spread_query(regs, qkey)[0]
        assert prev == 0.0
        for s in range(0, n_elems, 50):
            np_spread_update(regs, keys[s:s + 50], elems[s:s + 50])
            cur = np_spread_query(regs, qkey)[0]
            assert cur >= prev - 1e-12  # registers only grow
            prev = cur


class TestRetryProperty:
    """utils/retry.py invariants for arbitrary policy parameters: the
    delay schedule is bounded by [min(cap, base*2^i), that * (1+jitter)],
    has exactly attempts-1 entries, and is a pure function of the rng
    seed; retry_call's attempt accounting matches the schedule exactly."""

    @given(attempts=st.integers(1, 8),
           base=st.floats(1e-4, 1.0, allow_nan=False),
           cap=st.floats(1e-4, 4.0, allow_nan=False),
           jitter=st.floats(0.0, 1.0, allow_nan=False),
           seed=st.integers(0, 2**32 - 1))
    @settings(max_examples=100, deadline=None)
    def test_backoff_bounds_count_and_determinism(self, attempts, base,
                                                  cap, jitter, seed):
        import random

        from flow_pipeline_tpu.utils.retry import backoff_delays

        delays = list(backoff_delays(attempts, base, cap, jitter,
                                     random.Random(seed)))
        assert len(delays) == attempts - 1
        for i, d in enumerate(delays):
            lo = min(cap, base * (2 ** i))
            assert lo * (1.0 - 1e-12) <= d <= lo * (1.0 + jitter) \
                * (1.0 + 1e-12)
        assert delays == list(backoff_delays(attempts, base, cap, jitter,
                                             random.Random(seed)))

    @given(fails=st.integers(0, 10), attempts=st.integers(1, 8),
           seed=st.integers(0, 2**32 - 1))
    @settings(max_examples=100, deadline=None)
    def test_retry_call_attempt_accounting(self, fails, attempts, seed):
        import random

        from flow_pipeline_tpu.utils.retry import (backoff_delays,
                                                   retry_call)

        calls = {"n": 0}
        sleeps = []

        def fn():
            calls["n"] += 1
            if calls["n"] <= fails:
                raise OSError("transient")
            return "ok"

        if fails < attempts:
            assert retry_call(fn, attempts=attempts, sleep=sleeps.append,
                              rng=random.Random(seed)) == "ok"
            assert calls["n"] == fails + 1
            # the observed sleeps are exactly the schedule's prefix
            expect = list(backoff_delays(attempts, 0.05, 2.0, 0.25,
                                         random.Random(seed)))[:fails]
            assert sleeps == expect
        else:
            with pytest.raises(OSError):
                retry_call(fn, attempts=attempts, sleep=sleeps.append,
                           rng=random.Random(seed))
            assert calls["n"] == attempts  # the cap is a hard cap
            assert len(sleeps) == attempts - 1

    @given(attempts=st.integers(1, 8))
    @settings(max_examples=20, deadline=None)
    def test_non_retryable_propagates_first_call(self, attempts):
        from flow_pipeline_tpu.utils.retry import retry_call

        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise ValueError("deterministic bug")

        with pytest.raises(ValueError):
            retry_call(fn, attempts=attempts,
                       sleep=lambda _: pytest.fail("slept on a "
                                                   "non-retryable"))
        assert calls["n"] == 1


class TestFaultsProperty:
    """utils/faults.py stream discipline: a site's Bernoulli stream is a
    pure function of (plan seed, call index AT THAT SITE) — interleaving
    calls to other sites, or adding sites to the plan, must not shift
    it; snapshot() accounting is exact; the parse grammar round-trips."""

    @given(p_a=st.floats(0.0, 1.0, allow_nan=False),
           p_b=st.floats(0.0, 1.0, allow_nan=False),
           seed=st.integers(0, 10**6),
           schedule=st.lists(st.booleans(), max_size=60))
    @settings(max_examples=60, deadline=None)
    def test_per_site_stream_invariant_under_interleaving(
            self, p_a, p_b, seed, schedule):
        from flow_pipeline_tpu.utils.faults import FAULTS

        n_a = sum(schedule)
        try:
            FAULTS.configure(f"sink.write:p={p_a!r}@seed={seed}")
            ref = [FAULTS.should_fail("sink.write") for _ in range(n_a)]
            FAULTS.configure(f"sink.write:p={p_a!r};"
                             f"bus.poll:p={p_b!r}@seed={seed}")
            got = []
            for roll_a in schedule:
                if roll_a:
                    got.append(FAULTS.should_fail("sink.write"))
                else:
                    FAULTS.should_fail("bus.poll")
            assert got == ref
        finally:
            FAULTS.configure(None)

    @given(p=st.floats(0.0, 1.0, allow_nan=False),
           seed=st.integers(0, 10**6), rolls=st.integers(0, 80))
    @settings(max_examples=60, deadline=None)
    def test_snapshot_accounting_exact(self, p, seed, rolls):
        from flow_pipeline_tpu.utils.faults import FAULTS

        try:
            FAULTS.configure(f"sink.write:p={p!r}@seed={seed}")
            hits = sum(FAULTS.should_fail("sink.write")
                       for _ in range(rolls))
            snap = FAULTS.snapshot()["sink.write"]
            expected_rolls = rolls if p > 0.0 else 0  # p=0: no stream
            assert snap["rolls"] == expected_rolls
            assert snap["injected"] == hits
            assert snap["delayed"] == 0
        finally:
            FAULTS.configure(None)

    @given(p=st.floats(0.0, 1.0, allow_nan=False),
           seed=st.integers(0, 10**6), rolls=st.integers(0, 60))
    @settings(max_examples=40, deadline=None)
    def test_delay_sites_never_fail_and_share_the_stream(self, p, seed,
                                                         rolls):
        """A latency site's hits are the SAME Bernoulli stream as a
        failure site at the same (p, seed) — the delay only changes what
        a hit does — and should_fail() never reports them as failures."""
        from flow_pipeline_tpu.utils.faults import FAULTS

        try:
            FAULTS.configure(f"sink.write:p={p!r}@seed={seed}")
            fail_hits = [FAULTS.should_fail("sink.write")
                         for _ in range(rolls)]
            FAULTS.configure(
                f"sink.write:p={p!r}:delay=0.001@seed={seed}")
            delay_fails = [FAULTS.should_fail("sink.write")
                           for _ in range(rolls)]
            snap = FAULTS.snapshot().get("sink.write", {"delayed": 0})
            assert not any(delay_fails)  # latency sites never FAIL
            assert snap["delayed"] == sum(fail_hits)  # same stream
        finally:
            FAULTS.configure(None)

    @given(p=st.floats(0.0, 1.0, allow_nan=False),
           delay=st.floats(0.001, 60.0, allow_nan=False),
           seed=st.integers(0, 10**6))
    @settings(max_examples=60, deadline=None)
    def test_parse_plan_full_round_trip(self, p, delay, seed):
        from flow_pipeline_tpu.utils.faults import (parse_plan,
                                                    parse_plan_full)

        spec = f"sink.write:p={p!r}:delay={delay!r}@seed={seed}"
        sites, got_seed = parse_plan_full(spec)
        assert got_seed == seed
        assert sites == {"sink.write": (p, delay)}
        # the probability-only view drops the delay, keeps p
        probs, _ = parse_plan(spec)
        assert probs == {"sink.write": p}
        # delay-only form implies p=1
        sites2, _ = parse_plan_full(f"sink.write:delay={delay!r}")
        assert sites2 == {"sink.write": (1.0, delay)}
