"""Property-based fuzzing (hypothesis): the wire codec and the exact
aggregation path must hold for arbitrary well-typed inputs, not just
generator-shaped ones."""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from flow_pipeline_tpu.models.oracle import flows_5m
from flow_pipeline_tpu.models.window_agg import WindowAggConfig, WindowAggregator
from flow_pipeline_tpu.schema import (
    FlowBatch,
    FlowMessage,
    decode_message,
    encode_message,
)

u32 = st.integers(0, 2**32 - 1)
u64 = st.integers(0, 2**64 - 1)
u16 = st.integers(0, 2**16 - 1)
u8 = st.integers(0, 255)
addr = st.binary(min_size=0, max_size=16)

messages = st.builds(
    FlowMessage,
    type=st.integers(0, 4),
    time_received=u64,
    sampling_rate=u64,
    sequence_num=u32,
    time_flow_start=u64,
    time_flow_end=u64,
    src_addr=addr,
    dst_addr=addr,
    sampler_address=addr,
    bytes=u64,
    packets=u64,
    src_as=u32,
    dst_as=u32,
    in_if=u32,
    out_if=u32,
    proto=u8,
    src_port=u16,
    dst_port=u16,
    ip_tos=u8,
    forwarding_status=u8,
    ip_ttl=u8,
    tcp_flags=u8,
    etype=u16,
    icmp_type=u8,
    icmp_code=u8,
    ipv6_flow_label=st.integers(0, 2**20 - 1),
    flow_direction=st.integers(0, 1),
)


class TestWireProperty:
    @given(messages)
    @settings(max_examples=200, deadline=None)
    def test_roundtrip(self, msg):
        assert decode_message(encode_message(msg)) == msg

    @given(st.binary(max_size=64))
    @settings(max_examples=200, deadline=None)
    def test_decoder_never_crashes_unhandled(self, blob):
        # arbitrary bytes either decode or raise ValueError — nothing else
        try:
            decode_message(blob)
        except ValueError:
            pass


class TestWindowAggProperty:
    @given(
        st.lists(
            st.tuples(
                st.integers(1_000_000, 1_000_000 + 1800),  # time_received
                st.integers(64000, 64004),  # src_as
                st.integers(64000, 64004),  # dst_as
                st.sampled_from([0x0800, 0x86DD]),  # etype
                st.integers(0, 65535),  # bytes
                st.integers(0, 100),  # packets
            ),
            min_size=1,
            max_size=300,
        ),
        st.integers(1, 7),  # batch split factor
    )
    @settings(max_examples=30, deadline=None)
    def test_matches_oracle_for_any_stream(self, rows, splits):
        n = len(rows)
        batch = FlowBatch.empty(n)
        c = batch.columns
        for i, (ts, sas, das, et, by, pk) in enumerate(rows):
            c["time_received"][i] = ts
            c["src_as"][i] = sas
            c["dst_as"][i] = das
            c["etype"][i] = et
            c["bytes"][i] = by
            c["packets"][i] = pk
        agg = WindowAggregator(WindowAggConfig(batch_size=64))
        # feed in arbitrary chunk sizes (exercises padding + chunking)
        step = max(1, n // splits)
        for start in range(0, n, step):
            agg.update(batch.slice(start, start + step))
        out = agg.flush(force=True)
        oracle = flows_5m(batch)
        assert len(out["timeslot"]) == len(oracle["timeslot"])
        got = {
            (int(t), int(s), int(d), int(e)): (int(b), int(p), int(cn))
            for t, s, d, e, b, p, cn in zip(
                out["timeslot"], out["src_as"], out["dst_as"], out["etype"],
                out["bytes"], out["packets"], out["count"],
            )
        }
        for i in range(len(oracle["timeslot"])):
            key = (int(oracle["timeslot"][i]), int(oracle["src_as"][i]),
                   int(oracle["dst_as"][i]), int(oracle["etype"][i]))
            assert got[key] == (int(oracle["bytes"][i]),
                                int(oracle["packets"][i]),
                                int(oracle["count"][i]))


class TestCollectorDecodeProperty:
    """The UDP decoders must never raise anything but ValueError/struct
    hygiene regardless of datagram content — one spoofed packet must not
    kill a listener (collector/udp.py catches exactly those)."""

    @given(st.binary(max_size=512))
    @settings(max_examples=300, deadline=None)
    def test_netflow_decoder_contained(self, blob):
        import struct as struct_mod

        from flow_pipeline_tpu.collector import TemplateCache, decode_netflow

        try:
            decode_netflow(blob, TemplateCache())
        except (ValueError, struct_mod.error):
            pass

    @given(st.binary(max_size=512))
    @settings(max_examples=300, deadline=None)
    def test_sflow_decoder_contained(self, blob):
        import struct as struct_mod

        from flow_pipeline_tpu.collector import decode_sflow

        try:
            decode_sflow(blob)
        except (ValueError, struct_mod.error):
            pass

    @given(
        st.lists(st.binary(max_size=80), min_size=0, max_size=4),
        st.booleans(),
        st.integers(0, 3),
    )
    @settings(max_examples=200, deadline=None)
    def test_ipfix_varlen_payloads_decode_or_raise(self, payloads, long_form,
                                                   extra_fixed):
        """Structured fuzz of the RFC 7011 varlen path: ANY payload sizes
        (incl. 3-byte-form lengths and starved fixed tails from mutation)
        either decode to records with the right fixed values or raise
        ValueError — never a crash, never a silent mis-parse."""
        import struct as struct_mod

        from flow_pipeline_tpu.collector import TemplateCache, decode_netflow

        fields = [(1, 4), (371, 0xFFFF)] + [(2, 4)] * extra_fixed
        tmpl_body = struct_mod.pack(">HH", 310, len(fields))
        for t, ln in fields:
            tmpl_body += struct_mod.pack(">HH", t, ln)
        tmpl_set = struct_mod.pack(">HH", 2, 4 + len(tmpl_body)) + tmpl_body
        recs = b""
        for i, payload in enumerate(payloads):
            prefix = (bytes([255]) + struct_mod.pack(">H", len(payload))
                      if long_form else bytes([min(len(payload), 254)]))
            payload = payload[:254] if not long_form else payload
            recs += struct_mod.pack(">I", 100 + i) + prefix + payload
            recs += struct_mod.pack(">I", 10 + i) * extra_fixed
        data_set = struct_mod.pack(">HH", 310, 4 + len(recs)) + recs
        total = 16 + len(tmpl_set) + len(data_set)
        header = struct_mod.pack(">HHIII", 10, total, 1_700_000_000, 1, 5)
        msgs = decode_netflow(header + tmpl_set + data_set, TemplateCache())
        assert [m.bytes for m in msgs] == [100 + i
                                           for i in range(len(payloads))]
        assert all(m.packets == (10 + i if extra_fixed else 0)
                   for i, m in enumerate(msgs))


class TestSpaceSavingAdmission:
    """Adversarial admission at the eviction boundary (VERDICT r5 #5),
    fuzzed: arbitrary candidate streams against a deliberately narrow
    CMS. The bounds and the round driver live in test_models.
    drive_admission_rounds (also exercised there with a fixed seed, for
    environments without hypothesis); hypothesis explores the stream
    space — skewed, bursty, repeat-heavy — looking for a violation of
    the upper-bound / dropped-mass guarantees."""

    @settings(max_examples=10, deadline=None)
    @given(st.lists(
        st.lists(st.tuples(st.integers(1, 1200),
                           st.integers(1, 1000)),
                 min_size=1, max_size=16),
        min_size=3, max_size=8))
    def test_bounds_hold_under_narrow_cms(self, rounds):
        from test_models import drive_admission_rounds

        drive_admission_rounds(
            [[(k, float(v)) for k, v in pairs] for pairs in rounds])
