"""Sketch op unit tests against exact numpy counters (SURVEY.md §4:
"unit-test sketch kernels against exact numpy counters")."""

import jax.numpy as jnp
import numpy as np
import pytest

from flow_pipeline_tpu.ops import (
    QuantileSketchSpec,
    cms_add,
    cms_add_conservative,
    cms_init,
    cms_merge,
    cms_query,
    ewma_fold,
    ewma_init,
    rate_accumulate,
    bucket_of,
    topk_extract,
    topk_init,
    topk_merge,
    zscores,
)


def exact_counts(keys, values):
    agg = {}
    for k, v in zip(keys, values):
        agg[tuple(k)] = agg.get(tuple(k), 0) + v
    return agg


class TestCMS:
    def make(self, rng, n=512, n_keys=40, depth=4, width=1 << 12):
        keys = rng.integers(0, 2**32, size=(n_keys, 2), dtype=np.uint32)
        idx = rng.integers(0, n_keys, n)
        vals = rng.integers(1, 100, n)
        # pre-aggregate (the contract: unique keys per call)
        agg = {}
        for i, v in zip(idx, vals):
            agg[i] = agg.get(i, 0) + int(v)
        uk = np.array(sorted(agg))
        ukeys = keys[uk]
        uvals = np.array([[agg[i]] for i in uk], dtype=np.int32)
        return keys, ukeys, uvals, agg, uk

    @pytest.mark.parametrize("add_fn", [cms_add, cms_add_conservative])
    def test_upper_bound_and_accuracy(self, rng, add_fn):
        keys, ukeys, uvals, agg, uk = self.make(rng)
        sk = cms_init(1, 4, 1 << 12)
        sk = add_fn(sk, jnp.asarray(ukeys), jnp.asarray(uvals),
                    jnp.ones(len(ukeys), bool))
        est = np.asarray(cms_query(sk, jnp.asarray(ukeys)))[:, 0]
        true = np.array([agg[i] for i in uk], dtype=np.float64)
        assert (est >= true - 1e-3).all()  # upper bound
        # wide sketch, few keys -> estimates essentially exact
        np.testing.assert_allclose(est, true, rtol=1e-5)

    def test_conservative_tighter_than_linear(self, rng):
        # tiny width forces collisions; CU must never be looser
        keys, ukeys, uvals, agg, uk = self.make(rng, n_keys=300, width=128)
        lin = cms_add(cms_init(1, 2, 128), jnp.asarray(ukeys),
                      jnp.asarray(uvals), jnp.ones(len(ukeys), bool))
        con = cms_add_conservative(cms_init(1, 2, 128), jnp.asarray(ukeys),
                                   jnp.asarray(uvals), jnp.ones(len(ukeys), bool))
        e_lin = np.asarray(cms_query(lin, jnp.asarray(ukeys)))[:, 0]
        e_con = np.asarray(cms_query(con, jnp.asarray(ukeys)))[:, 0]
        true = np.array([agg[i] for i in uk])
        assert (e_con >= true - 1e-3).all()
        assert (e_con <= e_lin + 1e-3).all()
        assert e_con.sum() < e_lin.sum()  # strictly tighter somewhere

    def test_merge_equals_combined_stream(self, rng):
        keys, ukeys, uvals, agg, uk = self.make(rng)
        half = len(ukeys) // 2
        a = cms_add(cms_init(1, 4, 1 << 12), jnp.asarray(ukeys[:half]),
                    jnp.asarray(uvals[:half]), jnp.ones(half, bool))
        b = cms_add(cms_init(1, 4, 1 << 12), jnp.asarray(ukeys[half:]),
                    jnp.asarray(uvals[half:]), jnp.ones(len(ukeys) - half, bool))
        both = cms_add(cms_init(1, 4, 1 << 12), jnp.asarray(ukeys),
                       jnp.asarray(uvals), jnp.ones(len(ukeys), bool))
        np.testing.assert_allclose(
            np.asarray(cms_merge(a, b)), np.asarray(both), rtol=1e-6
        )

    def test_invalid_rows_ignored(self, rng):
        keys, ukeys, uvals, agg, uk = self.make(rng)
        valid = np.zeros(len(ukeys), bool)
        sk = cms_add(cms_init(1, 4, 1 << 12), jnp.asarray(ukeys),
                     jnp.asarray(uvals), jnp.asarray(valid))
        assert float(jnp.sum(sk)) == 0.0


class TestTopKTable:
    def test_exact_when_capacity_sufficient(self, rng):
        n_keys = 50
        keys = rng.integers(0, 2**31, size=(n_keys, 3), dtype=np.uint32)
        vals = rng.integers(1, 10_000, size=(n_keys, 1)).astype(np.float32)
        tk, tv = topk_init(64, 3, 1)
        # feed in 5 shuffled chunks of 10
        order = rng.permutation(n_keys)
        for c in range(5):
            idx = order[c * 10 : (c + 1) * 10]
            tk, tv = topk_merge(tk, tv, jnp.asarray(keys[idx]),
                                jnp.asarray(vals[idx]), jnp.ones(10, bool))
        out_k, out_v, valid = topk_extract(tk, tv, 64)
        out_k, out_v = np.asarray(out_k), np.asarray(out_v)
        assert np.asarray(valid).sum() == n_keys
        expect = vals[:, 0]
        top_true = keys[np.argsort(-expect)][:10]
        np.testing.assert_array_equal(out_k[:10], top_true)
        assert (np.diff(out_v[: n_keys, 0]) <= 0).all()

    def test_duplicate_keys_summed(self, rng):
        key = np.array([[7, 8]], dtype=np.uint32)
        tk, tv = topk_init(8, 2, 1)
        for v in (5.0, 10.0, 2.5):
            tk, tv = topk_merge(tk, tv, jnp.asarray(key),
                                jnp.asarray([[v]], np.float32), jnp.ones(1, bool))
        assert float(tv[0, 0]) == 17.5
        assert np.asarray(tk[0]).tolist() == [7, 8]

    def test_heavy_key_survives_eviction(self, rng):
        # one dominant key fed early, then floods of one-off keys
        tk, tv = topk_init(16, 1, 1)
        tk, tv = topk_merge(tk, tv, jnp.asarray([[42]], np.uint32),
                            jnp.asarray([[1e6]], np.float32), jnp.ones(1, bool))
        for c in range(8):
            noise_k = (rng.integers(100, 2**30, size=(32, 1))).astype(np.uint32)
            noise_v = rng.integers(1, 50, size=(32, 1)).astype(np.float32)
            tk, tv = topk_merge(tk, tv, jnp.asarray(noise_k),
                                jnp.asarray(noise_v), jnp.ones(32, bool))
        assert int(tk[0, 0]) == 42
        assert float(tv[0, 0]) == 1e6

    def test_empty_candidates_noop(self):
        tk, tv = topk_init(8, 2, 1)
        tk2, tv2 = topk_merge(tk, tv, jnp.zeros((4, 2), jnp.uint32),
                              jnp.ones((4, 1), jnp.float32), jnp.zeros(4, bool))
        np.testing.assert_array_equal(np.asarray(tk), np.asarray(tk2))

    def test_all_sentinel_key_excluded_not_slot_stealing(self):
        # the all-0xFFFFFFFF key tuple is the table's empty-slot marker and
        # therefore unrepresentable: a valid candidate carrying it must be
        # dropped at the merge boundary, never admitted where it would
        # occupy (or win) a capacity slot while being invisible to
        # topk_extract and zeroed on the next merge
        tk, tv = topk_init(2, 2, 1)
        cand_k = np.array(
            [[0xFFFFFFFF, 0xFFFFFFFF], [5, 6], [7, 8]], np.uint32
        )
        cand_v = np.array([[1e9], [10.0], [20.0]], np.float32)
        tk, tv = topk_merge(tk, tv, jnp.asarray(cand_k),
                            jnp.asarray(cand_v), jnp.ones(3, bool))
        out_k, out_v, valid = topk_extract(tk, tv, 2)
        assert np.asarray(valid).all()  # both capacity slots hold real keys
        assert np.asarray(out_k).tolist() == [[7, 8], [5, 6]]
        # a second merge keeps the real rows' mass intact
        tk, tv = topk_merge(tk, tv, jnp.asarray(cand_k),
                            jnp.asarray(cand_v), jnp.ones(3, bool))
        assert np.asarray(tv)[:, 0].tolist() == [40.0, 20.0]


class TestEWMA:
    def test_fold_matches_scalar_recurrence(self, rng):
        m = 8
        state = ewma_init(m)
        series = rng.integers(0, 100, size=(20, m)).astype(np.float32)
        for t in range(20):
            state = ewma_fold(state, jnp.asarray(series[t]), 0.3)
        # scalar reference for bucket 0
        mean = series[0, 0]
        var = 0.0
        for t in range(1, 20):
            d = series[t, 0] - mean
            mean = mean + 0.3 * d
            var = 0.7 * (var + 0.3 * d * d)
        assert abs(float(state[0][0]) - mean) < 1e-3
        assert abs(float(state[1][0]) - var) < 1e-2

    def test_zscore_flags_spike_only(self):
        m = 4
        state = ewma_init(m)
        for _ in range(30):
            state = ewma_fold(state, jnp.full(m, 100.0), 0.2)
        rates = jnp.asarray([100.0, 100.0, 3000.0, 100.0])
        z = np.asarray(zscores(state, rates, min_sigma=1.0))
        assert z[2] > 100
        assert abs(z[0]) < 1 and abs(z[3]) < 1

    def test_rate_accumulate_scatter(self, rng):
        keys = rng.integers(0, 2**32, size=(64, 4), dtype=np.uint32)
        b = np.asarray(bucket_of(jnp.asarray(keys), 128))
        vals = rng.integers(1, 10, 64).astype(np.int32)
        rates = rate_accumulate(jnp.zeros(128, jnp.float32), jnp.asarray(b),
                                jnp.asarray(vals), jnp.ones(64, bool))
        expect = np.zeros(128)
        np.add.at(expect, b, vals)
        np.testing.assert_allclose(np.asarray(rates), expect)


class TestQuantile:
    def test_quantiles_within_relative_error(self, rng):
        spec = QuantileSketchSpec(rel_err=0.01)
        data = rng.lognormal(8, 2, size=5000)
        hist = spec.init()
        hist = spec.add(hist, jnp.asarray(data))
        for q in (0.5, 0.9, 0.99):
            est = spec.quantile(np.asarray(hist), q)
            true = np.quantile(data, q)
            assert abs(est - true) / true < 0.05

    def test_merge_is_sum(self, rng):
        spec = QuantileSketchSpec()
        a = spec.add(spec.init(), jnp.asarray(rng.uniform(1, 1e6, 100)))
        b = spec.add(spec.init(), jnp.asarray(rng.uniform(1, 1e6, 100)))
        assert float(jnp.sum(a + b)) == 200.0

    def test_zeros_bucketed_separately(self):
        spec = QuantileSketchSpec()
        hist = spec.add(spec.init(), jnp.asarray([0.0, 0.0, 5.0]))
        assert float(hist[0]) == 2.0
        assert spec.quantile(np.asarray(hist), 0.5) == 0.0
