"""gRPC feed seam + supervisor recovery tests."""

import threading
import time

import numpy as np
import pytest

from flow_pipeline_tpu.engine import Supervisor, SupervisorConfig
from flow_pipeline_tpu.schema.message import FlowMessage
from flow_pipeline_tpu.transport import Consumer, InProcessBus

feed = pytest.importorskip("flow_pipeline_tpu.transport.feed")
if not feed.available():  # pragma: no cover
    pytest.skip("grpcio unavailable", allow_module_level=True)


class TestFeed:
    def make(self):
        bus = InProcessBus()
        server = feed.FeedServer(bus, address="127.0.0.1:0").start()
        client = feed.FeedClient(f"127.0.0.1:{server.port}")
        return bus, server, client

    def test_publish_messages_lands_on_bus(self):
        bus, server, client = self.make()
        try:
            msgs = [FlowMessage(bytes=i + 1, packets=1, src_as=65000)
                    for i in range(10)]
            assert client.publish_messages(msgs) == 10
            cons = Consumer(bus, fixedlen=True)
            got = []
            while (batch := cons.poll()) is not None:  # one batch/partition
                got.extend(batch.columns["bytes"].tolist())
            assert sorted(got) == list(range(1, 11))
        finally:
            client.close()
            server.stop()

    def test_publish_batch_native_path(self):
        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile

        bus, server, client = self.make()
        try:
            batch = FlowGenerator(ZipfProfile(n_keys=20), seed=3).batch(500)
            assert client.publish_batch(batch) == 500
            cons = Consumer(bus, fixedlen=True)
            total_rows = 0
            total_bytes = 0
            while (got := cons.poll(1000)) is not None:
                total_rows += len(got)
                total_bytes += int(got.columns["bytes"].sum())
            assert total_rows == 500
            assert total_bytes == int(batch.columns["bytes"].sum())
        finally:
            client.close()
            server.stop()

    def test_malformed_stream_rejected(self):
        import grpc

        bus, server, client = self.make()
        try:
            with pytest.raises(grpc.RpcError) as e:
                client.publish_frames(b"\xff\xff\xff garbage")
            assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        finally:
            client.close()
            server.stop()

    def test_concurrent_publishers(self):
        bus, server, client2 = self.make()
        clients = [feed.FeedClient(f"127.0.0.1:{server.port}")
                   for _ in range(4)]
        try:
            def blast(c):
                c.publish_messages([FlowMessage(bytes=1)] * 100)

            threads = [threading.Thread(target=blast, args=(c,))
                       for c in clients]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            total = sum(bus.end_offset("flows", p)
                        for p in range(bus.partitions("flows")))
            assert total == 400
        finally:
            for c in clients:
                c.close()
            client2.close()
            server.stop()


class TestSupervisor:
    def test_restarts_until_success(self):
        attempts = []

        class Flaky:
            def run(self):
                attempts.append(1)
                if len(attempts) < 3:
                    raise RuntimeError("transient")

            def finalize(self):
                pass

        sup = Supervisor(Flaky, SupervisorConfig(backoff_initial=0.01))
        sup.run()
        assert len(attempts) == 3
        assert sup.restarts == 2

    def test_crash_loop_gives_up(self):
        class AlwaysCrashes:
            def run(self):
                raise RuntimeError("permanent")

            def finalize(self):
                pass

        sup = Supervisor(
            AlwaysCrashes,
            SupervisorConfig(max_restarts=2, backoff_initial=0.01,
                             backoff_max=0.02),
        )
        with pytest.raises(RuntimeError, match="permanent"):
            sup.run()
        assert sup.restarts == 3  # 2 allowed restarts + the final crash
