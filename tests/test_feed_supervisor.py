"""gRPC feed seam + supervisor recovery tests."""

import threading
import time

import numpy as np
import pytest

from flow_pipeline_tpu.engine import Supervisor, SupervisorConfig
from flow_pipeline_tpu.schema.message import FlowMessage
from flow_pipeline_tpu.transport import Consumer, InProcessBus

feed = pytest.importorskip("flow_pipeline_tpu.transport.feed")
if not feed.available():  # pragma: no cover
    pytest.skip("grpcio unavailable", allow_module_level=True)


class TestFeed:
    def make(self):
        bus = InProcessBus()
        server = feed.FeedServer(bus, address="127.0.0.1:0").start()
        client = feed.FeedClient(f"127.0.0.1:{server.port}")
        return bus, server, client

    def test_publish_messages_lands_on_bus(self):
        bus, server, client = self.make()
        try:
            msgs = [FlowMessage(bytes=i + 1, packets=1, src_as=65000)
                    for i in range(10)]
            assert client.publish_messages(msgs) == 10
            cons = Consumer(bus, fixedlen=True)
            got = []
            while (batch := cons.poll()) is not None:  # one batch/partition
                got.extend(batch.columns["bytes"].tolist())
            assert sorted(got) == list(range(1, 11))
        finally:
            client.close()
            server.stop()

    def test_publish_batch_native_path(self):
        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile

        bus, server, client = self.make()
        try:
            batch = FlowGenerator(ZipfProfile(n_keys=20), seed=3).batch(500)
            assert client.publish_batch(batch) == 500
            cons = Consumer(bus, fixedlen=True)
            total_rows = 0
            total_bytes = 0
            while (got := cons.poll(1000)) is not None:
                total_rows += len(got)
                total_bytes += int(got.columns["bytes"].sum())
            assert total_rows == 500
            assert total_bytes == int(batch.columns["bytes"].sum())
        finally:
            client.close()
            server.stop()

    def test_malformed_stream_rejected(self):
        import grpc

        bus, server, client = self.make()
        try:
            with pytest.raises(grpc.RpcError) as e:
                client.publish_frames(b"\xff\xff\xff garbage")
            assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        finally:
            client.close()
            server.stop()

    def test_concurrent_publishers(self):
        bus, server, client2 = self.make()
        clients = [feed.FeedClient(f"127.0.0.1:{server.port}")
                   for _ in range(4)]
        try:
            def blast(c):
                c.publish_messages([FlowMessage(bytes=1)] * 100)

            threads = [threading.Thread(target=blast, args=(c,))
                       for c in clients]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            total = sum(bus.end_offset("flows", p)
                        for p in range(bus.partitions("flows")))
            assert total == 400
        finally:
            for c in clients:
                c.close()
            client2.close()
            server.stop()


def _go_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _go_mock_frames(n: int, seq_base: int, now: int) -> bytes:
    """Byte-for-byte replica of deploy/go-feed-client's mockFlows(): same
    field order, same proto3 zero-omission, same varint length framing.
    Keeping this in lockstep with main.go makes the Go client's exact
    byte stream a tested input even though the dev image has no Go
    toolchain (CI builds and runs the real binary)."""
    def addr(last: int) -> bytes:
        a = bytearray(16)
        a[0:4] = bytes((0x20, 0x01, 0x0D, 0xB8))
        a[15] = last
        return bytes(a)

    out = bytearray()
    for i in range(n):
        body = bytearray()
        for field, val in ((1, 1), (2, now), (3, 1), (4, seq_base + i)):
            if val:
                _go_varint(body, field << 3 | 0)
                _go_varint(body, val)
        for field, val in ((6, addr(i % 250)), (7, addr((i + 1) % 250))):
            _go_varint(body, field << 3 | 2)
            _go_varint(body, len(val))
            body += val
        for field, val in ((9, 100 + i % 1400), (10, 1 + i % 10),
                           (14, 65000 + i % 2), (15, 65000 + (i + 1) % 2),
                           (20, 6), (21, 1024 + i % 1000), (22, 443),
                           (30, 0x86DD), (38, now)):
            if val:
                _go_varint(body, field << 3 | 0)
                _go_varint(body, val)
        _go_varint(out, len(body))
        out += body
    return bytes(out)


class TestGoClientByteContract:
    """The exact byte stream deploy/go-feed-client emits, pushed through
    the live FeedServer and decoded by the normal consumer path."""

    def test_go_encoded_frames_roundtrip(self):
        bus = InProcessBus()
        server = feed.FeedServer(bus, address="127.0.0.1:0").start()
        client = feed.FeedClient(f"127.0.0.1:{server.port}")
        try:
            blob = _go_mock_frames(500, seq_base=100, now=1_700_000_000)
            assert client.publish_frames(blob) == 500
        finally:
            client.close()
            server.stop()
        from flow_pipeline_tpu.schema.batch import FlowBatch

        consumer = Consumer(bus, fixedlen=True)
        parts = []
        while (b := consumer.poll(1000)) is not None:
            parts.append(b)  # one partition per poll
        got = FlowBatch.concat(parts)
        assert len(got) == 500
        c = got.columns
        np.testing.assert_array_equal(
            np.sort(c["sequence_num"]), np.arange(100, 600, dtype=np.uint32))
        assert set(c["src_as"].tolist()) == {65000, 65001}
        assert set(c["etype"].tolist()) == {0x86DD}
        assert set(c["dst_port"].tolist()) == {443}
        assert c["bytes"].min() >= 100 and c["packets"].max() <= 10
        # the 2001:db8:: mock prefix survives the 4-word address packing
        assert (c["src_addr"][:, 0] == 0x20010DB8).all()


class TestSupervisor:
    def test_restarts_until_success(self):
        attempts = []

        class Flaky:
            def run(self):
                attempts.append(1)
                if len(attempts) < 3:
                    raise RuntimeError("transient")

            def finalize(self):
                pass

        sup = Supervisor(Flaky, SupervisorConfig(backoff_initial=0.01))
        sup.run()
        assert len(attempts) == 3
        assert sup.restarts == 2

    def test_crash_loop_gives_up(self):
        class AlwaysCrashes:
            def run(self):
                raise RuntimeError("permanent")

            def finalize(self):
                pass

        sup = Supervisor(
            AlwaysCrashes,
            SupervisorConfig(max_restarts=2, backoff_initial=0.01,
                             backoff_max=0.02),
        )
        with pytest.raises(RuntimeError, match="permanent"):
            sup.run()
        assert sup.restarts == 3  # 2 allowed restarts + the final crash
