"""Driver-seam guards: the round-4 artifact died in staging code no test
executed (`bench.py` staged [keys, values] by hand while the model read
config.scale_col too — KeyError at the first update). These tests run the
REAL driver entry points and the REAL bench staging paths at tiny shapes,
so a config-schema change that breaks the seam fails the suite instead of
the official artifact.

Methodology: bench's workload sizes are module-level constants precisely
so this file can shrink them (monkeypatch) and execute the genuine
functions end to end — replicating the staging logic here would guard
nothing.
"""

from __future__ import annotations

import json

import jax
import pytest

import __graft_entry__ as graft
import bench


def test_entry_compiles_and_runs():
    """The driver's single-chip compile check, verbatim."""
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    # state pytree comes back with the same structure
    assert type(out) is type(args[0])


def test_dryrun_multichip_small_mesh():
    """The driver's multi-chip dry run on a small virtual mesh (conftest
    forces the 8-device CPU platform)."""
    graft.dryrun_multichip(min(4, len(jax.devices())))


@pytest.fixture
def tiny_bench(monkeypatch):
    """Shrink every bench workload and skip the host probe (tests always
    run on the forced-CPU backend)."""
    monkeypatch.setattr(bench, "_PLATFORM", "cpu")
    monkeypatch.setattr(bench, "HH_BATCH", 512)
    monkeypatch.setattr(bench, "HH_STAGED", 2)
    monkeypatch.setattr(bench, "HH_STEPS", 2)
    monkeypatch.setattr(bench, "E2E_FLOWS", 16384)
    monkeypatch.setattr(bench, "SWEEP_BATCHES_CPU", (512,))
    monkeypatch.setattr(bench, "SWEEP_STEPS", 2)
    monkeypatch.setattr(bench, "HH_SKETCH_PAIRS", 1)
    monkeypatch.setattr(bench, "TRACE_BATCH", 512)
    monkeypatch.setattr(bench, "SHARDED_PER_CHIP", 256)
    monkeypatch.setattr(bench, "SHARDED_STEPS", 2)
    return bench


def _last_json(capsys) -> dict:
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    return json.loads(lines[-1])


def test_bench_main_staging(tiny_bench, monkeypatch, capsys):
    """`python bench.py` — the artifact the driver records every round."""
    monkeypatch.setattr(bench, "_SKIP_E2E_IN_MAIN", True)  # e2e below
    bench.main()
    out = _last_json(capsys)
    assert out["value"] > 0
    assert out["platform"] == "cpu"


def test_bench_e2e_staging(tiny_bench, capsys):
    """`python bench.py e2e` — full pipeline with the default model set."""
    bench._run_e2e  # the shared path main() also records
    stats = bench._run_e2e(tiny_bench.E2E_FLOWS, samples=1)
    assert stats["value"] > 0


def test_bench_hostsketch_staging(tiny_bench, capsys):
    """`python bench.py hostsketch` — the r8 sketch-backend A/B artifact
    (BENCH_r08.json's producer) at tiny shapes."""
    bench.bench_hostsketch()
    out = _last_json(capsys)
    assert out["metric"].startswith("e2e sketch-backend A/B")
    assert out["host_flows_per_sec"] > 0
    assert out["device_flows_per_sec"] > 0
    assert "device_apply_share_device_pct" in out
    assert "host_note" in out


def test_bench_sweep_staging(tiny_bench, capsys):
    bench.bench_sweep()
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    outs = [json.loads(l) for l in lines]
    best = next(o for o in outs if o["metric"] == "hh sweep best")
    assert best["value"] > 0
    # the r16 sketch-family paired A/B rides the same artifact
    ab = outs[-1]
    if "error" not in ab:
        assert "admission_share_invertible_pct" in ab
        assert ab["invertible_flows_per_sec"] > 0
        assert "inv" in ab["host_fused_phases_invertible"]


def test_bench_trace_staging(tiny_bench, capsys, tmp_path):
    bench.bench_trace(str(tmp_path / "trace"))
    out = _last_json(capsys)
    assert out["metric"] == "device trace captured"


def test_bench_sharded_staging(tiny_bench, capsys):
    n = min(4, len(jax.devices()))
    bench.bench_sharded(n)
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    outs = [json.loads(l) for l in lines]
    assert any("sharded heavy-hitter" in o["metric"] and o["value"] > 0
               for o in outs)
    assert any("sharded exact-agg" in o["metric"] and o["value"] > 0
               for o in outs)
