"""Driver-seam guards: the round-4 artifact died in staging code no test
executed (`bench.py` staged [keys, values] by hand while the model read
config.scale_col too — KeyError at the first update). These tests run the
REAL driver entry points and the REAL bench staging paths at tiny shapes,
so a config-schema change that breaks the seam fails the suite instead of
the official artifact.

Methodology: bench's workload sizes are module-level constants precisely
so this file can shrink them (monkeypatch) and execute the genuine
functions end to end — replicating the staging logic here would guard
nothing.
"""

from __future__ import annotations

import json

import jax
import pytest

import __graft_entry__ as graft
import bench


def test_entry_compiles_and_runs():
    """The driver's single-chip compile check, verbatim."""
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    # state pytree comes back with the same structure
    assert type(out) is type(args[0])


def test_dryrun_multichip_small_mesh():
    """The driver's multi-chip dry run on a small virtual mesh (conftest
    forces the 8-device CPU platform)."""
    graft.dryrun_multichip(min(4, len(jax.devices())))


@pytest.fixture
def tiny_bench(monkeypatch):
    """Shrink every bench workload and skip the host probe (tests always
    run on the forced-CPU backend)."""
    monkeypatch.setattr(bench, "_PLATFORM", "cpu")
    monkeypatch.setattr(bench, "HH_BATCH", 512)
    monkeypatch.setattr(bench, "HH_STAGED", 2)
    monkeypatch.setattr(bench, "HH_STEPS", 2)
    monkeypatch.setattr(bench, "E2E_FLOWS", 16384)
    monkeypatch.setattr(bench, "SWEEP_BATCHES_CPU", (512,))
    monkeypatch.setattr(bench, "SWEEP_STEPS", 2)
    monkeypatch.setattr(bench, "HH_SKETCH_PAIRS", 1)
    monkeypatch.setattr(bench, "TRACE_BATCH", 512)
    monkeypatch.setattr(bench, "SHARDED_PER_CHIP", 256)
    monkeypatch.setattr(bench, "SHARDED_STEPS", 2)
    return bench


def _last_json(capsys) -> dict:
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    return json.loads(lines[-1])


def test_bench_main_staging(tiny_bench, monkeypatch, capsys):
    """`python bench.py` — the artifact the driver records every round."""
    monkeypatch.setattr(bench, "_SKIP_E2E_IN_MAIN", True)  # e2e below
    bench.main()
    out = _last_json(capsys)
    assert out["value"] > 0
    assert out["platform"] == "cpu"


def test_bench_e2e_staging(tiny_bench, capsys):
    """`python bench.py e2e` — full pipeline with the default model set."""
    bench._run_e2e  # the shared path main() also records
    stats = bench._run_e2e(tiny_bench.E2E_FLOWS, samples=1)
    assert stats["value"] > 0


def test_bench_hostsketch_staging(tiny_bench, capsys):
    """`python bench.py hostsketch` — the r8 sketch-backend A/B artifact
    (BENCH_r08.json's producer) at tiny shapes."""
    bench.bench_hostsketch()
    out = _last_json(capsys)
    assert out["metric"].startswith("e2e sketch-backend A/B")
    assert out["host_flows_per_sec"] > 0
    assert out["device_flows_per_sec"] > 0
    assert "device_apply_share_device_pct" in out
    assert "host_note" in out


def test_bench_sweep_staging(tiny_bench, capsys):
    bench.bench_sweep()
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    outs = [json.loads(l) for l in lines]
    best = next(o for o in outs if o["metric"] == "hh sweep best")
    assert best["value"] > 0
    # the r16 sketch-family paired A/B rides the same artifact
    ab = outs[-1]
    if "error" not in ab:
        assert "admission_share_invertible_pct" in ab
        assert ab["invertible_flows_per_sec"] > 0
        assert "inv" in ab["host_fused_phases_invertible"]


@pytest.mark.slow  # ~9s of paired e2e legs; gated by `make fused-parity`
def test_bench_fused_staging(tiny_bench, monkeypatch, capsys):
    """`python bench.py fused` — the r10/r19 A/B artifact (BENCH_r19's
    producer) at tiny shapes: paired staged/fused legs, the flowspeed
    baseline-vs-threaded+C-lanes legs, the thread-scaling curve and the
    in-process lane-build sub-A/Bs all execute for real; only the
    subprocess SIMD A/B is stubbed (a novec compile + fresh interpreter
    spawns — its plumbing is exercised by the real bench run)."""
    monkeypatch.setattr(bench, "FUSED_PAIRS", 1)
    monkeypatch.setattr(bench, "FUSED_THREAD_POINTS", (2,))
    monkeypatch.setattr(bench, "_simd_ab",
                        lambda pairs=3: {"simd_ab_stubbed": True})
    real_lanes = bench._lane_build_native_ab
    monkeypatch.setattr(bench, "_lane_build_native_ab",
                        lambda: real_lanes(pairs=2, reps=2))
    real_r16 = bench._lane_build_ab
    monkeypatch.setattr(bench, "_lane_build_ab",
                        lambda: real_r16(pairs=2, reps=2))
    bench.bench_fused()
    out = _last_json(capsys)
    assert out["metric"].startswith("e2e fused-dataplane A/B")
    assert out["fused_flows_per_sec"] > 0
    assert out["staged_flows_per_sec"] > 0
    assert len(out["fused_pairs"]) == 1
    assert out["flowspeed_baseline_flows_per_sec"] > 0
    assert set(out["thread_scaling_flows_per_sec"]) == {"2"}
    assert out["lane_build_native_speedup"] > 0
    # the r19 attribution slot: the flowspeed leg built lanes in C
    assert "lanes" in out["host_group_phases_flowspeed"]
    assert out["host_group_phases_baseline"].get("lanes", 0.0) == 0.0
    assert "nproc" in out


def test_bench_kernels_staging(tiny_bench, capsys):
    """`python bench.py kernels` — the SIMD A/B's per-leg timing body
    (runs in subprocesses with FLOWDECODE_LIB in production)."""
    from flow_pipeline_tpu import native as native_lib

    if not native_lib.lanes_available():
        pytest.skip("libflowdecode lacks the r19 kernels")
    bench.bench_kernels()
    out = _last_json(capsys)
    assert out["metric"] == "r19 fused-kernel microbench"
    for key in ("inv_ns_per_row", "cms_ns_per_row", "lanes_ns_per_row"):
        assert out[key] > 0


def test_bench_trace_staging(tiny_bench, capsys, tmp_path):
    bench.bench_trace(str(tmp_path / "trace"))
    out = _last_json(capsys)
    assert out["metric"] == "device trace captured"


def test_bench_sharded_staging(tiny_bench, capsys):
    n = min(4, len(jax.devices()))
    bench.bench_sharded(n)
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    outs = [json.loads(l) for l in lines]
    assert any("sharded heavy-hitter" in o["metric"] and o["value"] > 0
               for o in outs)
    assert any("sharded exact-agg" in o["metric"] and o["value"] > 0
               for o in outs)
