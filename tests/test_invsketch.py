"""Invertible-sketch family parity suite (`make invertible-parity`).

The contract (docs/ARCHITECTURE.md "invertible sketch"): the three
twins — the pure-numpy reference (hostsketch/engine.py np_inv_*), the
jnp ops kernel (ops/invsketch.py, x64), and the native C kernels
(native/hostsketch.cc hs_inv_*, reached standalone and through
ff_fused_update) — are BIT-EXACT on every plane and decode the same
key set with the same exact values, at any thread count, u64 extremes
included. Downstream: extraction ranks exactly like the table family,
the worker pipelines (staged, fused, per-model fallback) emit
identical rows, checkpoints round-trip, and in the exact regime the
decoded ranking equals table mode bit-for-bit.
"""

from __future__ import annotations

import numpy as np
import pytest

from flow_pipeline_tpu import native
from flow_pipeline_tpu.hostsketch.engine import (
    HostSketchEngine,
    inv_decode_state,
    inv_extract,
    np_inv_decode,
    np_inv_key_hash,
    np_inv_update,
)
from flow_pipeline_tpu.hostsketch.state import (
    HostInvState,
    from_device_state,
    host_inv_init,
    is_inv_state,
)
from flow_pipeline_tpu.models.heavy_hitter import (
    HeavyHitterConfig,
    InvState,
    hh_init,
    inv_init,
)

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover
    HAVE_HYPOTHESIS = False

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")

PLANES, DEPTH, WIDTH, KW = 3, 4, 1 << 10, 5


def _state(planes=PLANES, depth=DEPTH, width=WIDTH, kw=KW):
    return HostInvState(
        cms=np.zeros((planes, depth, width), np.uint64),
        keysum=np.zeros((depth, width, kw), np.uint64),
        keycheck=np.zeros((depth, width), np.uint64),
    )


def _groups(n, kw=KW, planes=PLANES, seed=0, key_space=None):
    """(keys [n, kw] u32 unique-ish, vals [n, planes] f32 with the
    count plane last) — the group-table granularity every backend
    consumes."""
    rng = np.random.default_rng(seed)
    if key_space is None:
        keys = rng.integers(0, 2**32, size=(n, kw),
                            dtype=np.uint64).astype(np.uint32)
    else:
        keys = key_space[rng.integers(0, len(key_space), size=n)]
    vals = rng.integers(1, 1500, size=(n, planes)).astype(np.float32)
    vals[:, -1] = rng.integers(1, 64, size=n).astype(np.float32)
    return keys, vals


def _assert_states_equal(a, b):
    assert np.array_equal(a.cms, b.cms)
    assert np.array_equal(a.keysum, b.keysum)
    assert np.array_equal(a.keycheck, b.keycheck)


# ---------------------------------------------------------------------------
# twin parity: numpy vs native vs jnp
# ---------------------------------------------------------------------------


class TestTwinParity:
    def test_native_update_matches_numpy(self):
        if not native.inv_available():
            pytest.skip("native invertible kernels not built")
        keys, vals = _groups(700)
        ref, nat = _state(), _state()
        np_inv_update(ref, keys, vals)
        native.hs_inv_update(nat.cms, nat.keysum, nat.keycheck, keys,
                             vals, None, threads=1)
        _assert_states_equal(ref, nat)

    @pytest.mark.parametrize("threads", [1, 2, 8])
    def test_native_update_thread_count_deterministic(self, threads):
        if not native.inv_available():
            pytest.skip("native invertible kernels not built")
        keys, vals = _groups(5000, seed=3)
        ref, nat = _state(), _state()
        np_inv_update(ref, keys, vals)
        native.hs_inv_update(nat.cms, nat.keysum, nat.keycheck, keys,
                             vals, None, threads=threads)
        _assert_states_equal(ref, nat)

    def test_native_decode_matches_numpy(self):
        if not native.inv_available():
            pytest.skip("native invertible kernels not built")
        keys, vals = _groups(400, seed=5)
        st = _state()
        np_inv_update(st, keys, vals)
        k1, v1 = np_inv_decode(st.cms, st.keysum, st.keycheck)
        k2, v2 = inv_decode_state(st)  # native path + canonical sort
        assert np.array_equal(k1, k2)
        assert np.array_equal(v1, v2)

    def test_jnp_twins_match_numpy(self):
        from jax.experimental import enable_x64

        with enable_x64():
            import jax.numpy as jnp

            from flow_pipeline_tpu.ops import invsketch as inv

            keys, vals = _groups(300, seed=7)
            cms, ks, kc = inv.inv_init(PLANES, DEPTH, WIDTH, KW)
            cms, ks, kc = inv.inv_update(cms, ks, kc, jnp.asarray(keys),
                                         jnp.asarray(vals))
            ref = _state()
            np_inv_update(ref, keys, vals)
            assert np.array_equal(np.asarray(cms), ref.cms)
            assert np.array_equal(np.asarray(ks), ref.keysum)
            assert np.array_equal(np.asarray(kc), ref.keycheck)
            k1, v1 = np_inv_decode(ref.cms, ref.keysum, ref.keycheck)
            k2, v2 = inv.inv_decode(cms, ks, kc)
            assert np.array_equal(k1, k2)
            assert np.array_equal(v1, v2)

    def test_jnp_valid_mask_matches_sliced(self):
        from jax.experimental import enable_x64

        with enable_x64():
            import jax.numpy as jnp

            from flow_pipeline_tpu.ops import invsketch as inv

            keys, vals = _groups(200, seed=11)
            valid = np.zeros(200, bool)
            valid[:137] = True
            cms, ks, kc = inv.inv_init(PLANES, DEPTH, WIDTH, KW)
            cms, ks, kc = inv.inv_update(cms, ks, kc, jnp.asarray(keys),
                                         jnp.asarray(vals),
                                         jnp.asarray(valid))
            ref = _state()
            np_inv_update(ref, keys[:137], vals[:137])
            assert np.array_equal(np.asarray(cms), ref.cms)
            assert np.array_equal(np.asarray(ks), ref.keysum)
            assert np.array_equal(np.asarray(kc), ref.keycheck)

    def test_jnp_merge_is_element_sum(self):
        from jax.experimental import enable_x64

        with enable_x64():
            import jax.numpy as jnp

            from flow_pipeline_tpu.ops import invsketch as inv

            ka, va = _groups(100, seed=1)
            kb, vb = _groups(100, seed=2)
            a = inv.inv_update(*inv.inv_init(PLANES, DEPTH, WIDTH, KW),
                               jnp.asarray(ka), jnp.asarray(va))
            b = inv.inv_update(*inv.inv_init(PLANES, DEPTH, WIDTH, KW),
                               jnp.asarray(kb), jnp.asarray(vb))
            merged = inv.inv_merge(a, b)
            both = inv.inv_update(*inv.inv_update(
                *inv.inv_init(PLANES, DEPTH, WIDTH, KW),
                jnp.asarray(ka), jnp.asarray(va)),
                jnp.asarray(kb), jnp.asarray(vb))
            for m, t in zip(merged, both):
                assert np.array_equal(np.asarray(m), np.asarray(t))

    def test_u64_extremes_clamp_and_wrap_identically(self):
        """Addends at/past the f32->u64 envelope edge (negatives, NaN,
        inf, ~2^64) must clamp identically everywhere, and repeated
        near-cap adds must WRAP identically (mod-2^64 linearity)."""
        keys = np.arange(6 * KW, dtype=np.uint32).reshape(6, KW)
        vals = np.array([
            [1.0, 2.0, 1.0],
            [-5.0, float("nan"), 1.0],
            [float("inf"), 2.0**63, 2.0**40],
            [2.0**64, 1.8446742e19, 1.0],
            [0.0, 1.0, 2.0**52],
            [3.0, 4.0, 2.0**31],
        ], np.float32)
        ref = _state()
        for _ in range(3):  # force u64 wrap in keysum/keycheck
            np_inv_update(ref, keys, vals)
        if native.inv_available():
            nat = _state()
            for _ in range(3):
                native.hs_inv_update(nat.cms, nat.keysum, nat.keycheck,
                                     keys, vals, None, threads=2)
            _assert_states_equal(ref, nat)
        from jax.experimental import enable_x64

        with enable_x64():
            import jax.numpy as jnp

            from flow_pipeline_tpu.ops import invsketch as inv

            state = inv.inv_init(PLANES, DEPTH, WIDTH, KW)
            for _ in range(3):
                state = inv.inv_update(*state, jnp.asarray(keys),
                                       jnp.asarray(vals))
            assert np.array_equal(np.asarray(state[0]), ref.cms)
            assert np.array_equal(np.asarray(state[1]), ref.keysum)
            assert np.array_equal(np.asarray(state[2]), ref.keycheck)

    def test_update_linearity_chunk_granularity_irrelevant(self):
        """The whole design premise: folding one big group table equals
        folding its pieces in any order — bit-exactly."""
        keys, vals = _groups(900, seed=13)
        whole = _state()
        np_inv_update(whole, keys, vals)
        parts = _state()
        for lo, hi in ((600, 900), (0, 300), (300, 600)):
            np_inv_update(parts, keys[lo:hi], vals[lo:hi])
        _assert_states_equal(whole, parts)

    def test_degenerate_shapes_rejected(self):
        if not native.inv_available():
            pytest.skip("native invertible kernels not built")
        keys, vals = _groups(4)
        st = _state()
        with pytest.raises(ValueError):
            native.hs_inv_update(
                np.zeros((0, DEPTH, WIDTH), np.uint64), st.keysum,
                st.keycheck, keys, vals, None)

    def test_n_zero_is_noop(self):
        st = _state()
        np_inv_update(st, np.zeros((0, KW), np.uint32),
                      np.zeros((0, PLANES), np.float32))
        assert not st.cms.any() and not st.keysum.any()
        if native.inv_available():
            native.hs_inv_update(st.cms, st.keysum, st.keycheck,
                                 np.zeros((0, KW), np.uint32),
                                 np.zeros((0, PLANES), np.float32), None)
            assert not st.cms.any()

    if HAVE_HYPOTHESIS:
        @given(st.integers(0, 2**32 - 1), st.integers(1, 400),
               st.integers(0, 2**20))
        @settings(max_examples=25, deadline=None)
        def test_property_random_streams_bit_exact(self, seed, n, vmax):
            rng = np.random.default_rng(seed)
            keys = rng.integers(0, 2**32, size=(n, 3),
                                dtype=np.uint64).astype(np.uint32)
            vals = rng.integers(0, max(vmax, 1),
                                size=(n, 2)).astype(np.float32)
            ref = HostInvState(
                cms=np.zeros((2, 2, 128), np.uint64),
                keysum=np.zeros((2, 128, 3), np.uint64),
                keycheck=np.zeros((2, 128), np.uint64))
            np_inv_update(ref, keys, vals)
            if native.inv_available():
                nat = HostInvState(
                    cms=np.zeros((2, 2, 128), np.uint64),
                    keysum=np.zeros((2, 128, 3), np.uint64),
                    keycheck=np.zeros((2, 128), np.uint64))
                native.hs_inv_update(nat.cms, nat.keysum, nat.keycheck,
                                     keys, vals, None, threads=3)
                _assert_states_equal(ref, nat)
                k1, v1 = np_inv_decode(ref.cms, ref.keysum, ref.keycheck)
                k2, v2 = inv_decode_state(nat)
                assert np.array_equal(k1, k2)
                assert np.array_equal(v1, v2)


# ---------------------------------------------------------------------------
# decode semantics
# ---------------------------------------------------------------------------


class TestDecode:
    def test_full_recovery_with_exact_values_in_sparse_regime(self):
        """Keys << buckets: peeling recovers EVERY key with its exact
        u64 per-plane sums (the decode-at-close exactness claim)."""
        rng = np.random.default_rng(21)
        uniq = rng.integers(0, 2**32, size=(250, KW),
                            dtype=np.uint64).astype(np.uint32)
        rows = uniq[rng.integers(0, 250, size=2000)]
        vals = rng.integers(1, 1000, size=(2000, PLANES)).astype(
            np.float32)
        st = _state()
        np_inv_update(st, rows, vals)
        keys, dec = np_inv_decode(st.cms, st.keysum, st.keycheck)
        # exact oracle
        kv = rows.view([("", np.uint32)] * KW).reshape(-1)
        uk, inv_idx = np.unique(kv, return_inverse=True)
        exact = np.zeros((len(uk), PLANES), np.uint64)
        np.add.at(exact, inv_idx, vals.astype(np.uint64))
        assert len(keys) == len(uk)
        got = {keys[i].tobytes(): dec[i] for i in range(len(keys))}
        for i in range(len(uk)):
            assert np.array_equal(got[uk[i].tobytes()], exact[i])

    def test_decode_is_lex_sorted_canonical(self):
        keys, vals = _groups(120, seed=31)
        st = _state()
        np_inv_update(st, keys, vals)
        k, _ = np_inv_decode(st.cms, st.keysum, st.keycheck)
        order = np.lexsort(k.T[::-1])
        assert np.array_equal(order, np.arange(len(k)))

    def test_empty_sketch_decodes_empty(self):
        st = _state()
        k, v = np_inv_decode(st.cms, st.keysum, st.keycheck)
        assert k.shape == (0, KW) and v.shape == (0, PLANES)
        tk, tv = inv_extract(st, 16)
        assert (tk == np.uint32(0xFFFFFFFF)).all() and not tv.any()

    def test_extract_ranks_primary_desc_lex_ties(self):
        """inv_extract reproduces the table family's (primary desc, lex
        key asc) ranking rule, truncated to capacity."""
        st = HostInvState(
            cms=np.zeros((2, DEPTH, WIDTH), np.uint64),
            keysum=np.zeros((DEPTH, WIDTH, 2), np.uint64),
            keycheck=np.zeros((DEPTH, WIDTH), np.uint64))
        keys = np.array([[5, 1], [2, 9], [2, 3], [7, 7]], np.uint32)
        vals = np.array([[30, 1], [10, 1], [10, 1], [40, 1]], np.float32)
        np_inv_update(st, keys, vals)
        tk, tv = inv_extract(st, 3)
        assert np.array_equal(tk, np.array(
            [[7, 7], [5, 1], [2, 3]], np.uint32))
        assert np.array_equal(tv[:, 0],
                              np.array([40, 30, 10], np.float32))

    def test_all_sentinel_key_dropped_at_extract(self):
        st = _state()
        keys = np.vstack([np.full((1, KW), 0xFFFFFFFF, np.uint32),
                          np.arange(KW, dtype=np.uint32)[None, :]])
        vals = np.full((2, PLANES), 9.0, np.float32)
        np_inv_update(st, keys, vals)
        tk, _ = inv_extract(st, 8)
        real = (tk != np.uint32(0xFFFFFFFF)).any(axis=1)
        assert int(real.sum()) == 1

    def test_inv_key_hash_protocol_pinned(self):
        """The checksum hash is a cross-twin protocol constant: pin a
        few words so an accidental reimplementation cannot drift."""
        h = np_inv_key_hash(np.array([[0, 0], [1, 2], [0xFFFFFFFF, 0]],
                                     np.uint32))
        assert h.dtype == np.uint64
        assert len(set(h.tolist())) == 3
        # self-consistency vs native
        if native.inv_available():
            st = HostInvState(
                cms=np.zeros((1, 1, 8), np.uint64),
                keysum=np.zeros((1, 8, 2), np.uint64),
                keycheck=np.zeros((1, 8), np.uint64))
            k = np.array([[1, 2]], np.uint32)
            v = np.array([[1.0]], np.float32)
            native.hs_inv_update(st.cms, st.keysum, st.keycheck, k, v,
                                 None)
            assert st.keycheck.sum() == np_inv_key_hash(k)[0]


# ---------------------------------------------------------------------------
# engine / model / state plumbing
# ---------------------------------------------------------------------------


INV_CFG = HeavyHitterConfig(
    key_cols=("src_addr", "dst_addr"), width=1 << 12, capacity=256,
    batch_size=4096, hh_sketch="invertible")


class TestEngineAndModel:
    def test_engine_update_native_equals_numpy(self):
        keys, vals = _groups(800, kw=8, seed=41)
        engines = [HostSketchEngine([INV_CFG], use_native="numpy")]
        if native.inv_available():
            engines.append(HostSketchEngine([INV_CFG],
                                            use_native="native"))
        states = []
        for eng in engines:
            eng.reset(0)
            eng.update(0, keys, vals, len(keys))
            states.append(eng.states[0])
        for st in states[1:]:
            _assert_states_equal(states[0], st)

    def test_engine_export_import_round_trip(self):
        eng = HostSketchEngine([INV_CFG], use_native="auto")
        keys, vals = _groups(100, kw=8, seed=43)
        eng.update(0, keys, vals, len(keys))
        exported = eng.export_state(0)
        assert isinstance(exported, InvState)
        assert is_inv_state(exported)
        back = from_device_state(exported)
        _assert_states_equal(eng.states[0], back)
        # fresh leaves: mutating the engine must not touch the export
        eng.update(0, keys, vals, len(keys))
        assert not np.array_equal(exported.cms, eng.states[0].cms)

    def test_hh_init_dispatches_on_family(self):
        assert isinstance(hh_init(INV_CFG), InvState)
        assert hh_init(INV_CFG).cms.dtype == np.uint64
        with pytest.raises(ValueError):
            hh_init(HeavyHitterConfig(hh_sketch="wat"))

    def test_model_update_top_exact_regime(self):
        """Per-model fallback path: exact sums, exact ranking."""
        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
        from flow_pipeline_tpu.models.heavy_hitter import (
            HeavyHitterModel)

        model = HeavyHitterModel(INV_CFG)
        batch = FlowGenerator(ZipfProfile(n_keys=60), seed=3).batch(4000)
        model.update(batch)
        top = model.top(50)
        assert top["valid"].sum() == 50
        primary = top["bytes"][top["valid"]].astype(np.float64)
        assert (np.diff(primary) <= 0).all()  # ranked descending
        # decode values are exact, so est (CMS upper bound) dominates
        assert (top["bytes_est"][top["valid"]]
                >= top["bytes"][top["valid"]]).all()
        lazy = model.top_lazy(50)
        model.update(batch)  # mutates in place — the capture must not move
        top2 = lazy()
        for col in top:
            assert np.array_equal(top[col], top2[col])

    def test_exact_regime_matches_table_mode_bit_for_bit(self):
        """Capacity >= keys, plain update, integer envelope: the
        invertible ranking must equal the table family's rows exactly
        (values AND est columns — same cms planes, same ranking)."""
        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
        from flow_pipeline_tpu.models.heavy_hitter import (
            HeavyHitterModel)

        common = dict(key_cols=("src_addr", "dst_addr"), width=1 << 12,
                      capacity=512, batch_size=4096,
                      conservative=False)
        batch = FlowGenerator(ZipfProfile(n_keys=300), seed=9).batch(8000)
        m_inv = HeavyHitterModel(HeavyHitterConfig(
            hh_sketch="invertible", **common))
        m_tab = HeavyHitterModel(HeavyHitterConfig(**common))
        m_inv.update(batch)
        m_tab.update(batch)
        t_inv, t_tab = m_inv.top(100), m_tab.top(100)
        assert set(t_inv) == set(t_tab)
        for col in t_tab:
            assert np.array_equal(np.asarray(t_inv[col]),
                                  np.asarray(t_tab[col])), col

    def test_checkpoint_round_trip_and_mismatch_skip(self, tmp_path):
        from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
        from flow_pipeline_tpu.engine.windowed import WindowedHeavyHitter
        from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile

        path = str(tmp_path / "ckpt")

        def make_worker(cfg):
            return StreamWorker(None, {
                "talkers": WindowedHeavyHitter(cfg, k=16)},
                config=WorkerConfig(checkpoint_path=path, prefetch=0,
                                    sketch_backend="host",
                                    host_assist="on", obs_audit="off"))

        w = make_worker(INV_CFG)
        batch = FlowGenerator(ZipfProfile(n_keys=40), seed=5).batch(2000)
        with w.lock:
            w.models["talkers"].update(batch)
            w.snapshot_and_commit()
        w2 = make_worker(INV_CFG)
        assert w2.restore()
        st1 = w.models["talkers"].model.state
        st2 = w2.models["talkers"].model.state
        assert isinstance(st2, InvState) and st2.cms.dtype == np.uint64
        _assert_states_equal(st1, st2)
        # restoring the invertible checkpoint into a TABLE-config model
        # must skip loudly, not corrupt
        w3 = make_worker(HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr"), width=1 << 12,
            capacity=256, batch_size=4096))
        assert w3.restore()
        st3 = w3.models["talkers"].model.state
        assert not is_inv_state(st3)
        assert not np.asarray(st3.cms).any()  # fresh, not restored


# ---------------------------------------------------------------------------
# pipeline parity: staged vs fused vs per-model fallback
# ---------------------------------------------------------------------------


def _run_worker(hh_sketch, fused, sketch_backend="host", n_flows=30_000,
                audit="off", extra_flags=()):
    from flow_pipeline_tpu.cli import (_batch_frames, _build_models,
                                       _common_flags, _gen_flags,
                                       _make_generator, _processor_flags)
    from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
    from flow_pipeline_tpu.transport import Consumer, InProcessBus
    from flow_pipeline_tpu.utils.flags import FlagSet

    fs = _processor_flags(_gen_flags(_common_flags(FlagSet("t"))))
    vals = fs.parse(["-produce.profile", "zipf", "-hh.sketch", hh_sketch,
                     "-zipf.keys", "400", "-model.ports=false",
                     "-model.ddos=false", "-sketch.capacity", "512",
                     *extra_flags])
    bus = InProcessBus()
    bus.create_topic("flows", 2)
    gen = _make_generator(vals)
    produced = 0
    while produced < n_flows:
        bus.produce_many("flows", _batch_frames(gen.batch(8192)))
        produced += 8192

    class Sink:
        def __init__(self):
            self.tables = {}

        def write(self, table, rows):
            self.tables.setdefault(table, []).append(rows)

    sink = Sink()
    worker = StreamWorker(
        Consumer(bus, fixedlen=True), _build_models(vals), [sink],
        WorkerConfig(poll_max=8192, snapshot_every=0,
                     sketch_backend=sketch_backend,
                     ingest_native_group=True, ingest_fused=fused,
                     obs_audit=audit))
    worker.run(stop_when_idle=True)
    return sink.tables


def _assert_tables_equal(t1, t2):
    assert set(t1) == set(t2)
    for tab in t1:
        assert len(t1[tab]) == len(t2[tab])
        for r1, r2 in zip(t1[tab], t2[tab]):
            assert set(r1) == set(r2)
            for col in r1:
                assert np.array_equal(np.asarray(r1[col]),
                                      np.asarray(r2[col])), (tab, col)


class TestPipelineParity:
    def test_fused_equals_staged_invertible(self):
        if not (native.fused_available() and native.inv_available()):
            pytest.skip("fused native dataplane not built")
        staged = _run_worker("invertible", "off")
        fused = _run_worker("invertible", "on")
        _assert_tables_equal(staged, fused)

    def test_fallback_equals_host_pipeline_invertible(self):
        """sketch_backend=device routes invertible families to the
        per-model numpy path — same rows as the host engine."""
        host = _run_worker("invertible", "off")
        fallback = _run_worker("invertible", "off",
                               sketch_backend="device")
        _assert_tables_equal(host, fallback)

    def test_audit_is_observational_in_invertible_mode(self):
        if not (native.fused_available() and native.inv_available()):
            pytest.skip("fused native dataplane not built")
        off = _run_worker("invertible", "on", audit="off")
        on = _run_worker("invertible", "on", audit="sample")
        _assert_tables_equal(off, on)

    def test_fused_plan_marks_invertible_families(self):
        from flow_pipeline_tpu.cli import (_build_models, _common_flags,
                                           _gen_flags, _processor_flags)
        from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
        from flow_pipeline_tpu.utils.flags import FlagSet

        if not (native.fused_available() and native.inv_available()):
            pytest.skip("fused native dataplane not built")
        fs = _processor_flags(_gen_flags(_common_flags(FlagSet("t"))))
        vals = fs.parse(["-hh.sketch", "invertible",
                         "-model.ports=false", "-model.ddos=false"])
        w = StreamWorker(None, _build_models(vals), [],
                         WorkerConfig(sketch_backend="host",
                                      host_assist="on", prefetch=0,
                                      ingest_fused="on",
                                      obs_audit="off"))
        for _, plan in w.fused._fused_trees:
            assert plan.invertible is not None and plan.invertible.all()

    def test_flag_registered_and_validated(self):
        from flow_pipeline_tpu.utils.flags import KNOWN_FLAGS

        assert "hh.sketch" in KNOWN_FLAGS
        with pytest.raises(ValueError):
            HostSketchEngine([HeavyHitterConfig(hh_sketch="bogus")])

    def test_build_info_carries_hh_sketch_label(self):
        from flow_pipeline_tpu.cli import (_build_models, _common_flags,
                                           _gen_flags, _processor_flags)
        from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
        from flow_pipeline_tpu.obs import REGISTRY
        from flow_pipeline_tpu.utils.flags import FlagSet

        fs = _processor_flags(_gen_flags(_common_flags(FlagSet("t"))))
        vals = fs.parse(["-hh.sketch", "invertible",
                         "-model.ports=false", "-model.ddos=false"])
        StreamWorker(None, _build_models(vals), [],
                     WorkerConfig(sketch_backend="host",
                                  host_assist="on", prefetch=0,
                                  obs_audit="off"))
        g = REGISTRY.gauge("flow_build_info",
                           "build/runtime identity (constant 1; labels "
                           "pin the native capability set, trace mode, "
                           "sketch backend, and mesh role)")
        assert 'hh_sketch="invertible"' in g.render()


# ---------------------------------------------------------------------------
# merge / codec citizenship (unit level; mesh e2e lives in test_mesh.py)
# ---------------------------------------------------------------------------


class TestMergeCodec:
    def test_payload_round_trip_and_plain_sum_merge(self):
        from flow_pipeline_tpu.mesh import codec
        from flow_pipeline_tpu.mesh.merge import merge_hh

        cfg = HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr"), width=1 << 10,
            capacity=64, hh_sketch="invertible")
        shards = []
        whole = host_inv_init(cfg)
        for seed in (1, 2, 3):
            st = host_inv_init(cfg)
            keys, vals = _groups(150, kw=8, seed=seed)
            np_inv_update(st, keys, vals)
            np_inv_update(whole, keys, vals)
            payload = codec.decode(codec.encode(codec.hh_payload(st)))
            assert payload["kind"] == "hh_inv"
            assert payload["cms"].dtype == np.uint64
            shards.append(payload)
        merged = merge_hh(shards, cfg)
        # merge == element-wise u64 sum == the union-stream state
        assert np.array_equal(merged["cms"], whole.cms)
        assert np.array_equal(merged["keysum"], whole.keysum)
        assert np.array_equal(merged["keycheck"], whole.keycheck)
        # and the merged table view is the union decode
        tk, tv = inv_extract(whole, cfg.capacity)
        assert np.array_equal(merged["table_keys"], tk)
        assert np.array_equal(merged["table_vals"], tv)

    def test_mixed_family_payloads_rejected(self):
        from flow_pipeline_tpu.mesh import codec
        from flow_pipeline_tpu.mesh.merge import merge_hh

        cfg = HeavyHitterConfig(key_cols=("src_addr", "dst_addr"),
                                width=1 << 10, capacity=64)
        inv_p = codec.hh_payload(host_inv_init(
            HeavyHitterConfig(key_cols=("src_addr", "dst_addr"),
                              width=1 << 10, capacity=64,
                              hh_sketch="invertible")))
        tab_p = codec.hh_payload(hh_init(cfg))
        with pytest.raises(ValueError):
            merge_hh([inv_p, tab_p], cfg)

    def test_capture_model_ships_inv_payload(self):
        from flow_pipeline_tpu.mesh import codec
        from flow_pipeline_tpu.models.heavy_hitter import (
            HeavyHitterModel)

        model = HeavyHitterModel(INV_CFG)
        payload = codec.capture_model(model)
        assert payload["kind"] == "hh_inv"
        assert set(payload) >= {"cms", "keysum", "keycheck"}

    def test_frozen_cms_preserves_u64_planes(self):
        from flow_pipeline_tpu.hostsketch.state import frozen_cms

        st = host_inv_init(INV_CFG)
        st.cms[0, 0, 0] = np.uint64(2**53 + 1)  # f32-lossy value
        out = frozen_cms(st)
        assert out.dtype == np.uint64
        assert out[0, 0, 0] == np.uint64(2**53 + 1)
        out[0, 0, 0] = 0  # fresh copy, never aliases engine state
        assert st.cms[0, 0, 0] == np.uint64(2**53 + 1)


# ---------------------------------------------------------------------------
# -hh.sketch=auto: the r19 cascade flip (cli._build_models)
# ---------------------------------------------------------------------------


class TestAutoSketchResolution:
    """`auto` (the r19 default) flips CASCADE families — key sets that
    are strict subsets of another enabled hh family's — to the
    invertible sketch when the host sketch dataplane serves; root
    families and every non-host deployment keep the table family, so a
    default worker never lands on the per-model numpy fallback."""

    def _models(self, *flags):
        from flow_pipeline_tpu.cli import (_build_models, _common_flags,
                                           _gen_flags, _processor_flags)
        from flow_pipeline_tpu.utils.flags import FlagSet

        fs = _processor_flags(_gen_flags(_common_flags(FlagSet("t"))))
        return _build_models(fs.parse(list(flags)))

    def _sketch(self, models):
        return {name: m.model.config.hh_sketch
                for name, m in models.items()
                if getattr(getattr(m, "model", None), "snapshot_kind",
                           None) == "windowed_hh"}

    def test_auto_flips_cascade_families_on_host_backend(self):
        got = self._sketch(self._models("-sketch.backend", "host"))
        assert got == {"top_talkers": "table",
                       "top_src_ips": "invertible",
                       "top_dst_ips": "invertible"}

    def test_auto_keeps_table_off_host_backend(self):
        # device backend: the invertible family would fall back to the
        # per-model numpy path — auto must never choose that
        got = self._sketch(self._models())
        assert set(got.values()) == {"table"}

    def test_auto_keeps_table_without_cascade_parent(self):
        # no talkers family -> the IP families are roots, not cascades
        got = self._sketch(self._models("-sketch.backend", "host",
                                        "-model.talkers=false"))
        assert got == {"top_src_ips": "table", "top_dst_ips": "table"}

    def test_explicit_override_beats_auto(self):
        got = self._sketch(self._models("-sketch.backend", "host",
                                        "-hh.sketch", "invertible"))
        assert set(got.values()) == {"invertible"}
        got = self._sketch(self._models("-sketch.backend", "host",
                                        "-hh.sketch", "table"))
        assert set(got.values()) == {"table"}

    @pytest.mark.slow  # two full workers; gated by `make invertible-parity`
    def test_auto_exact_regime_equals_table_bit_for_bit(self):
        """The flip's exactness pin: capacity (512) >= distinct keys
        (400-key zipf), so BOTH families are in their exact regime and
        the auto worker's sink rows — cascade families invertible,
        root table — must be bit-identical to the all-table worker's."""
        if not (native.fused_available() and native.inv_available()):
            pytest.skip("fused native dataplane not built")
        auto = _run_worker("auto", "on",
                           extra_flags=("-sketch.backend", "host"))
        table = _run_worker("table", "on",
                            extra_flags=("-sketch.backend", "host"))
        _assert_tables_equal(auto, table)
