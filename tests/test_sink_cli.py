"""Sink + CLI tests: record normalization, SQLite storage, Postgres SQL
generation, and the CLI surface (pipeline demo, mocker -out / processor -in
file roundtrip, flag errors)."""

import sqlite3

import numpy as np
import pytest

from flow_pipeline_tpu.cli import main
from flow_pipeline_tpu.sink import MemorySink, SQLiteSink, rows_to_records
from flow_pipeline_tpu.sink.postgres import insert_sql


class TestRecords:
    def test_columnar_rows(self):
        rows = {
            "timeslot": np.array([300, 300], np.uint64),
            "src_as": np.array([65000, 65001], np.uint64),
            "bytes": np.array([10, 20], np.uint64),
        }
        recs = rows_to_records(rows)
        assert recs == [
            {"timeslot": 300, "src_as": 65000, "bytes": 10},
            {"timeslot": 300, "src_as": 65001, "bytes": 20},
        ]

    def test_valid_mask_filters(self):
        rows = {
            "bytes": np.array([1, 2], np.uint64),
            "valid": np.array([True, False]),
        }
        assert len(rows_to_records(rows)) == 1

    def test_ipv4_and_ipv6_render(self):
        v4 = np.array([0, 0, 0, (10 << 24) | (0 << 16) | (0 << 8) | 7], np.uint32)
        v6 = np.array([0x20010DB8, 0, 0, 0x1234], np.uint32)
        rows = {"dst_addr": np.stack([v4, v6]), "bytes": np.array([1, 2], np.uint64)}
        recs = rows_to_records(rows)
        assert recs[0]["dst_addr"] == "10.0.0.7"
        assert recs[1]["dst_addr"] == "2001:db8::1234"


class TestSQLite:
    def test_known_tables(self):
        sink = SQLiteSink()
        sink.write("flows_5m", {
            "timeslot": np.array([300], np.uint64),
            "src_as": np.array([65000], np.uint64),
            "dst_as": np.array([65001], np.uint64),
            "etype": np.array([0x86DD], np.uint64),
            "bytes": np.array([99], np.uint64),
            "packets": np.array([3], np.uint64),
            "count": np.array([1], np.uint64),
        })
        assert sink.query("SELECT bytes FROM flows_5m") == [(99,)]

    def test_migrates_pre_r4_file_missing_scaled_columns(self, tmp_path):
        """A .db created before the sampling-scaled columns landed must
        be ALTERed at sink init, not crash-loop on the first insert
        ('no column named bytes_scaled') — CREATE TABLE IF NOT EXISTS is
        a no-op on existing files (ADVICE r5)."""
        path = str(tmp_path / "pre_r4.db")
        conn = sqlite3.connect(path)
        conn.execute(
            "CREATE TABLE flows_5m (timeslot INTEGER, src_as INTEGER, "
            "dst_as INTEGER, etype INTEGER, bytes INTEGER, "
            "packets INTEGER, count INTEGER)")
        conn.execute(
            "INSERT INTO flows_5m VALUES (0, 1, 2, 3, 10, 1, 1)")
        conn.commit()
        conn.close()
        sink = SQLiteSink(path)
        sink.write("flows_5m", {
            "timeslot": np.array([300], np.uint64),
            "src_as": np.array([65000], np.uint64),
            "dst_as": np.array([65001], np.uint64),
            "etype": np.array([0x86DD], np.uint64),
            "bytes": np.array([99], np.uint64),
            "packets": np.array([3], np.uint64),
            "count": np.array([1], np.uint64),
            "bytes_scaled": np.array([990], np.uint64),
            "packets_scaled": np.array([30], np.uint64),
        })
        assert sink.query(
            "SELECT bytes, bytes_scaled FROM flows_5m "
            "WHERE timeslot = 300") == [(99, 990)]
        # pre-migration rows survive with NULL scaled columns
        assert sink.query(
            "SELECT bytes_scaled FROM flows_5m WHERE timeslot = 0"
        ) == [(None,)]
        sink.close()

    def test_unknown_table_journaled(self):
        sink = SQLiteSink()
        sink.write("mystery", [{"a": 1}])
        rows = sink.query("SELECT table_name, record FROM journal")
        assert rows[0][0] == "mystery"

    def test_topk_rank_assigned(self):
        sink = SQLiteSink()
        sink.write("top_talkers", {
            "timeslot": np.array([0, 0], np.uint64),
            "bytes": np.array([100, 50], np.uint64),
            "valid": np.array([True, True]),
        })
        assert sink.query("SELECT rank, bytes FROM top_talkers ORDER BY rank") == [
            (0, 100), (1, 50),
        ]


class TestPostgresSQL:
    def test_insert_sql_multirow_single_statement(self):
        sql, args = insert_sql("flows_5m", [
            {"timeslot": 300, "src_as": 1, "dst_as": 2, "etype": 3,
             "bytes": 4, "packets": 5, "count": 6,
             "bytes_scaled": 40, "packets_scaled": 50},
            {"timeslot": 600, "src_as": 7, "dst_as": 8, "etype": 9,
             "bytes": 10, "packets": 11, "count": 12,
             "bytes_scaled": 100, "packets_scaled": 110},
        ])
        assert sql.startswith('INSERT INTO "flows_5m"')
        assert sql.count("(%s") == 2  # one VALUES group per record
        assert args == [300, 1, 2, 3, 4, 5, 6, 40, 50,
                        600, 7, 8, 9, 10, 11, 12, 100, 110]

    def test_missing_fields_become_none(self):
        _, args = insert_sql("ddos_alerts", [{"rate": 1.5}])
        assert args.count(None) == 5


class TestCLI:
    def test_usage(self, capsys):
        assert main([]) == 2
        assert main(["-h"]) == 0
        assert "mocker" in capsys.readouterr().out

    def test_unknown_command(self, capsys):
        assert main(["fnord"]) == 2

    def test_unknown_flag(self, capsys):
        # flowlint: disable=flag-registry -- deliberately unregistered: this IS the unknown-flag rejection test
        assert main(["pipeline", "-not.a.flag", "x"]) == 2
        assert "not.a.flag" in capsys.readouterr().err

    def test_pipeline_to_sqlite(self, tmp_path):
        db = str(tmp_path / "flows.db")
        rc = main([
            "pipeline", "-produce.count", "2000", "-produce.rate", "50",
            "-processor.batch", "512", "-sink", f"sqlite:{db}",
            "-metrics.addr", "", "-model.ddos=false",
        ])
        assert rc == 0
        conn = sqlite3.connect(db)
        total = conn.execute("SELECT SUM(count) FROM flows_5m").fetchone()[0]
        assert total == 2000

    def test_mocker_file_then_processor(self, tmp_path):
        frames = str(tmp_path / "frames.bin")
        db = str(tmp_path / "flows.db")
        assert main(["mocker", "-out", frames, "-produce.count", "1500",
                     "-produce.rate", "50"]) == 0
        assert main(["processor", "-in", frames, "-processor.batch", "512",
                     "-sink", f"sqlite:{db}", "-metrics.addr", "",
                     "-model.ddos=false", "-model.talkers=false"]) == 0
        conn = sqlite3.connect(db)
        assert conn.execute("SELECT SUM(count) FROM flows_5m").fetchone()[0] == 1500

    def test_pipeline_with_mesh(self, tmp_path):
        # -processor.mesh 8 runs the sharded models over the CPU mesh
        db = str(tmp_path / "mesh.db")
        rc = main([
            "pipeline", "-produce.count", "4000", "-produce.rate", "40",
            "-processor.batch", "128", "-processor.mesh", "8",
            "-sink", f"sqlite:{db}", "-metrics.addr", "",
            "-model.ddos=false", "-sketch.width", str(1 << 12),
            "-sketch.capacity", "64",
        ])
        assert rc == 0
        conn = sqlite3.connect(db)
        assert conn.execute("SELECT SUM(count) FROM flows_5m").fetchone()[0] == 4000
        assert conn.execute("SELECT COUNT(*) FROM top_talkers").fetchone()[0] > 0

    def test_mocker_then_inserter_raw_rows(self, tmp_path):
        frames = str(tmp_path / "frames.bin")
        db = str(tmp_path / "raw.db")
        assert main(["mocker", "-out", frames, "-produce.count", "300"]) == 0
        assert main(["inserter", "-in", frames, "-sqlite", db]) == 0
        conn = sqlite3.connect(db)
        n, su = conn.execute("SELECT COUNT(*), SUM(bytes) FROM flows").fetchone()
        assert n == 300 and su > 0
        ip = conn.execute("SELECT src_ip FROM flows LIMIT 1").fetchone()[0]
        assert ip.startswith("2001:db8:0:1::")
