"""flowchaos: coordinator crash recovery (write-ahead journal), sink
retry + dead-letter + replay, the deterministic fault-injection layer,
and the chaos soak — `make chaos-parity` runs this file.

The r12 exactness-under-churn contract extended from "a worker dies" to
"anything dies": the kill-COORDINATOR-mid-stream leg must keep merged
sink output bit-exact vs the single-worker oracle, injected sink faults
must dead-letter (never crash the worker) and replay back to row-set
equality, and seeded mesh-transport faults must not lose or
double-count a single window."""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from flow_pipeline_tpu.cli import (_build_models, _common_flags,
                                   _gen_flags, _processor_flags)
from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.mesh import (InProcessMesh, MeshCoordinator,
                                    MeshMember, ModelSpec,
                                    produce_sharded, spec_from_models)
from flow_pipeline_tpu.mesh import codec
from flow_pipeline_tpu.mesh.journal import (CoordinatorJournal,
                                            replay_journal)
from flow_pipeline_tpu.models.oracle import exact_groupby
from flow_pipeline_tpu.models.window_agg import WindowAggConfig
from flow_pipeline_tpu.schema.batch import FlowBatch
from flow_pipeline_tpu.sink import MemorySink, ResilientSink
from flow_pipeline_tpu.sink.resilient import (deadletter_files,
                                              replay_deadletter)
from flow_pipeline_tpu.transport import Consumer, InProcessBus
from flow_pipeline_tpu.utils.faults import FAULTS, parse_plan
from flow_pipeline_tpu.utils.flags import KNOWN_FLAGS, FlagSet
from flow_pipeline_tpu.utils.retry import retry_call

N_KEYS = 200
N_FLOWS = 24_000
PARTITIONS = 8
BATCH = 4096
# Default modeled rate keeps the whole stream inside ONE 5-minute
# window (the r12 oracle regime: the single worker IS a valid top-K
# oracle only when no window closes mid-stream — interleaved partition
# consumption otherwise makes IT drop late rows the per-partition mesh
# members never see as late). The multi-window crash leg below uses
# MULTIWIN_RATE with the flows_5m model only, whose late-partial
# semantics stay exact under any consumption order.
RATE = 100_000.0
MULTIWIN_RATE = 60.0

TOP_COLS = ("src_addr", "dst_addr", "src_port", "dst_port", "proto",
            "bytes", "packets", "count", "timeslot")


@pytest.fixture(autouse=True)
def _faults_disarmed():
    """The fault plan is process state (like TRACER): every test starts
    and ends disarmed, whatever happened before it."""
    FAULTS.configure(None)
    yield
    FAULTS.configure(None)


def _vals(*extra):
    fs = _processor_flags(_gen_flags(_common_flags(FlagSet("test"))))
    return fs.parse([
        "-produce.profile", "zipf", "-zipf.keys", str(N_KEYS),
        "-model.ports=false", "-model.ddos=false", "-model.ips=false",
        "-processor.batch", str(BATCH), "-sketch.capacity", "512",
        *extra,
    ])


def _stream_batches(n_flows=N_FLOWS, seed=0, rate=RATE):
    gen = FlowGenerator(ZipfProfile(n_keys=N_KEYS, alpha=1.2), seed=seed,
                        rate=rate)
    out, done = [], 0
    while done < n_flows:
        n = min(8192, n_flows - done)
        out.append(gen.batch(n))
        done += n
    return out


def _make_bus(n_flows=N_FLOWS, partitions=PARTITIONS, rate=RATE):
    bus = InProcessBus()
    bus.create_topic("flows", partitions)
    for batch in _stream_batches(n_flows, rate=rate):
        produce_sharded(bus, "flows", batch, partitions)
    return bus


class ListSink:
    def __init__(self):
        self.tables = {}

    def write(self, table, rows):
        self.tables.setdefault(table, []).append(rows)


def _fold_flows5m(tables):
    acc = {}
    for rows in tables.get("flows_5m", []):
        for i in range(len(rows["timeslot"])):
            key = (int(rows["timeslot"][i]), int(rows["src_as"][i]),
                   int(rows["dst_as"][i]), int(rows["etype"][i]))
            v = acc.setdefault(key, np.zeros(3, np.uint64))
            v += np.array([rows["bytes"][i], rows["packets"][i],
                           rows["count"][i]], np.uint64)
    return acc


def _oracle_flows5m(rate=RATE):
    full = FlowBatch.concat(_stream_batches(rate=rate))
    o = exact_groupby(full, ["src_as", "dst_as", "etype"],
                      ["bytes", "packets"])
    return {
        (int(o["timeslot"][i]), int(o["src_as"][i]), int(o["dst_as"][i]),
         int(o["etype"][i])):
        np.array([o["bytes"][i], o["packets"][i], o["count"][i]],
                 np.uint64)
        for i in range(len(o["timeslot"]))
    }


def _assert_flows5m_oracle_exact(tables, rate=RATE):
    oracle = _oracle_flows5m(rate)
    fold = _fold_flows5m(tables)
    assert set(fold) == set(oracle)
    for k in oracle:
        assert (fold[k] == oracle[k]).all()


def _assert_topk_tables_equal(t1, t2, table="top_talkers"):
    """Every emitted top-K window matches, slot by slot (the streams
    may span several windows)."""
    def by_slot(windows):
        out = {}
        for rows in windows:
            v = np.asarray(rows["valid"])
            assert v.any()
            out[int(np.asarray(rows["timeslot"])[v][0])] = rows
        return out

    w1, w2 = by_slot(t1[table]), by_slot(t2[table])
    assert set(w1) == set(w2)
    for slot in w1:
        r1, r2 = w1[slot], w2[slot]
        v1, v2 = np.asarray(r1["valid"]), np.asarray(r2["valid"])
        assert int(v1.sum()) == int(v2.sum())
        for col in TOP_COLS:
            a, b = np.asarray(r1[col])[v1], np.asarray(r2[col])[v2]
            assert a.shape == b.shape and (a == b).all(), (slot, col)


def _run_single_worker(vals, sink, rate=RATE):
    worker = StreamWorker(
        Consumer(_make_bus(rate=rate), "flows", fixedlen=True),
        _build_models(vals), [sink],
        WorkerConfig(poll_max=BATCH, snapshot_every=0,
                     sketch_backend=vals["sketch.backend"]))
    worker.run(stop_when_idle=True)
    return worker


# ---------------------------------------------------------------------------
# fault plan parsing + determinism
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_parse_plan(self):
        sites, seed = parse_plan(
            "sink.write:p=0.05;mesh.submit:p=0.02@seed=7")
        assert sites == {"sink.write": 0.05, "mesh.submit": 0.02}
        assert seed == 7

    def test_parse_defaults_seed_zero(self):
        sites, seed = parse_plan("sink.write:p=1")
        assert sites == {"sink.write": 1.0} and seed == 0

    @pytest.mark.parametrize("bad", [
        "nope.site:p=0.1", "sink.write", "sink.write:q=0.1",
        "sink.write:p=1.5", "sink.write:p=0.1@tick=3",
    ])
    def test_malformed_plans_rejected(self, bad):
        with pytest.raises(ValueError):
            parse_plan(bad)

    def test_off_mode_is_one_attribute_read(self):
        FAULTS.configure(None)
        assert FAULTS.active is False
        # the guarded call-site pattern short-circuits on the attribute
        assert not (FAULTS.active and FAULTS.should_fail("sink.write"))

    def test_deterministic_per_site_streams(self):
        FAULTS.configure("sink.write:p=0.3;mesh.submit:p=0.3@seed=42")
        a = [FAULTS.should_fail("sink.write") for _ in range(64)]
        FAULTS.configure("sink.write:p=0.3;mesh.submit:p=0.3@seed=42")
        # interleave calls to ANOTHER site: sink.write's stream must not
        # shift (per-site independent RNGs — the determinism contract)
        b = []
        for _ in range(64):
            FAULTS.should_fail("mesh.submit")
            b.append(FAULTS.should_fail("sink.write"))
        assert a == b
        assert any(a) and not all(a)

    def test_check_raises_oserror_subclass(self):
        FAULTS.configure("sink.write:p=1@seed=1")
        with pytest.raises(OSError):
            FAULTS.check("sink.write")
        snap = FAULTS.snapshot()
        assert snap["sink.write"]["injected"] == 1

    def test_env_fallback_arms_the_flag(self, monkeypatch):
        monkeypatch.setenv("FLOWTPU_FAULTS", "sink.write:p=0.5@seed=9")
        vals = _vals()
        assert vals["faults"] == "sink.write:p=0.5@seed=9"

    def test_chaos_flags_registered(self):
        for flag in ("faults", "sink.retries", "sink.deadletter",
                     "mesh.journal", "replay.dir", "replay.delete"):
            assert flag in KNOWN_FLAGS


class TestRetry:
    def test_retries_then_succeeds(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionResetError("transient")
            return "ok"

        slept = []
        assert retry_call(fn, attempts=4, base=0.1, cap=1.0, jitter=0.0,
                          sleep=slept.append) == "ok"
        assert len(calls) == 3
        assert slept == [0.1, 0.2]  # exponential, jitter off

    def test_exhaustion_raises_last_error(self):
        def fn():
            raise ConnectionRefusedError("down")

        slept = []
        with pytest.raises(ConnectionRefusedError):
            retry_call(fn, attempts=3, base=0.1, cap=0.15, jitter=0.0,
                       sleep=slept.append)
        assert slept == [0.1, 0.15]  # capped

    def test_member_retries_http_transport_exceptions(self):
        """A coordinator dying MID-RESPONSE surfaces as
        http.client.HTTPException / json.JSONDecodeError — NOT OSError.
        The member's transport choke point must normalize them into the
        retryable class, or the exact outage flowchaos exists to
        survive kills the member thread (review finding)."""
        import http.client
        import json as _json

        member = MeshMember("t", None, None, None)
        calls = []

        def flaky_sync():
            calls.append(1)
            if len(calls) == 1:
                raise http.client.IncompleteRead(b"partial")
            if len(calls) == 2:
                raise _json.JSONDecodeError("truncated", "{", 1)
            return {"ok": True}

        assert member._coord_call("sync", flaky_sync) == {"ok": True}
        assert len(calls) == 3
        assert member.m_retries.value(op="sync") >= 2

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("deterministic bug")

        with pytest.raises(ValueError):
            retry_call(fn, attempts=5, sleep=lambda _: None)
        assert len(calls) == 1


# ---------------------------------------------------------------------------
# journal wire format
# ---------------------------------------------------------------------------


class TestJournal:
    def test_round_trip(self, tmp_path):
        j = CoordinatorJournal(str(tmp_path))
        j.append("sub", {"member": "w0"}, b"\x00\x01payload")
        j.append("epoch", {"epoch": 3, "reason": "join"})
        j.append("merged", {"model": "flows_5m", "slot": 300})
        j.sync()
        j.close()
        got = list(replay_journal(j.path))
        assert got == [("sub", {"member": "w0"}, b"\x00\x01payload"),
                       ("epoch", {"epoch": 3, "reason": "join"}, b""),
                       ("merged", {"model": "flows_5m", "slot": 300},
                        b"")]

    def test_append_only_across_incarnations(self, tmp_path):
        j1 = CoordinatorJournal(str(tmp_path))
        j1.append("epoch", {"epoch": 1, "reason": "join"})
        j1.close()
        j2 = CoordinatorJournal(str(tmp_path))
        j2.append("epoch", {"epoch": 2, "reason": "recovery"})
        j2.close()
        kinds = [(k, m["epoch"]) for k, m, _ in replay_journal(j2.path)]
        assert kinds == [("epoch", 1), ("epoch", 2)]

    def test_torn_tail_recovers_prefix(self, tmp_path):
        j = CoordinatorJournal(str(tmp_path))
        j.append("sub", {"member": "w0"}, b"A" * 64)
        j.append("sub", {"member": "w1"}, b"B" * 64)
        j.close()
        size = os.path.getsize(j.path)
        with open(j.path, "r+b") as f:
            f.truncate(size - 7)  # crash mid-append of the last record
        got = list(replay_journal(j.path))
        assert [m["member"] for _, m, _ in got] == ["w0"]

    def test_corrupt_record_stops_replay(self, tmp_path):
        j = CoordinatorJournal(str(tmp_path))
        j.append("sub", {"member": "w0"}, b"A" * 32)
        j.append("sub", {"member": "w1"}, b"B" * 32)
        j.close()
        with open(j.path, "r+b") as f:
            f.seek(-5, os.SEEK_END)
            f.write(b"XXXXX")
        got = list(replay_journal(j.path))
        assert [m["member"] for _, m, _ in got] == ["w0"]

    def test_bad_magic_rejected(self, tmp_path):
        p = tmp_path / "coordinator.journal"
        p.write_bytes(b"not a journal")
        with pytest.raises(ValueError, match="magic"):
            list(replay_journal(str(p)))

    def test_torn_magic_starts_fresh(self, tmp_path):
        """A crash during the very FIRST init can tear the 7-byte magic
        write; that must not wedge every later startup (nothing was
        ever acked against the file)."""
        p = tmp_path / "coordinator.journal"
        p.write_bytes(b"FJR")  # torn first write
        assert list(replay_journal(str(p))) == []  # recover to empty
        j = CoordinatorJournal(str(tmp_path))  # re-inits the file
        j.append("epoch", {"epoch": 1, "reason": "join"})
        j.close()
        assert [k for k, _, _ in replay_journal(str(p))] == ["epoch"]


# ---------------------------------------------------------------------------
# coordinator recovery protocol units (synthetic payloads, no jax models)
# ---------------------------------------------------------------------------


def _wagg_spec():
    cfg = WindowAggConfig(key_cols=("src_as",), value_cols=("bytes",),
                          window_seconds=300, scale_col=None,
                          batch_size=256)
    return ModelSpec("flows_5m", "wagg", cfg, 0, 300)


def _contrib(ranges, wm, closed=None, open_=None, final=False,
             release=False, flows=0):
    return {"ranges": ranges, "watermark": wm, "closed": closed or {},
            "open": open_ or {}, "final": final, "release": release,
            "flows": flows}


def _wagg_win(key, val):
    return {"flows_5m": codec.wagg_payload(
        {(key,): np.array([val, 1], np.uint64)})}


class TestCoordinatorRecovery:
    def make(self, tmp_path, partitions=1, sink=None, **kw):
        return MeshCoordinator([_wagg_spec()], partitions,
                               sinks=[sink] if sink else (),
                               journal=str(tmp_path / "wal"), **kw)

    def test_recovers_frontier_epoch_and_merged_ledger(self, tmp_path):
        s1 = ListSink()
        c = self.make(tmp_path, sink=s1)
        c.join("a")
        c.sync("a")
        # merges immediately (wm past the barrier) -> emitted + journaled
        assert c.submit("a", codec.encode(_contrib(
            {0: [0, 10]}, wm=900, closed={300: _wagg_win(7, 50)})))["ok"]
        assert len(s1.tables["flows_5m"]) == 1
        epoch_before = c.epoch
        # crash: drop c; a fresh coordinator recovers from the journal
        s2 = ListSink()
        c2 = self.make(tmp_path, sink=s2)
        assert c2.status()["covered"] == [10]
        assert c2.epoch > epoch_before
        # the merged window must NOT re-emit (its rows are in the sinks)
        assert "flows_5m" not in s2.tables
        # ...but late contributions for it still register as late
        late0 = c2._m["late"].value(model="flows_5m")
        c2.join("a")
        c2.sync("a")
        c2.submit("a", codec.encode(_contrib(
            {0: [10, 11]}, wm=901, closed={300: _wagg_win(7, 4)})))
        assert c2._m["late"].value(model="flows_5m") == late0 + 1

    def test_pending_window_merges_after_recovery(self, tmp_path):
        """Accepted but unmerged at crash time: the contribution must
        survive into the recovered barrier and merge exactly once."""
        c = self.make(tmp_path)
        c.join("a")
        c.sync("a")
        # wm=100 < slot+window: stays pending
        c.submit("a", codec.encode(_contrib(
            {0: [0, 8]}, wm=100, closed={300: _wagg_win(2, 30)})))
        s2 = ListSink()
        c2 = self.make(tmp_path, sink=s2)
        assert c2.status()["covered"] == [8]
        c2.join("b")
        c2.sync("b")
        c2.submit("b", codec.encode(_contrib(
            {0: [8, 12]}, wm=700, closed={300: _wagg_win(2, 12)},
            final=True)))
        rows = c2.merged_rows("flows_5m", 300)
        assert len(rows) == 1
        # pre-crash contribution (30) + successor (12): nothing lost,
        # nothing double-counted
        assert int(rows[0]["bytes"][0]) == 42

    def test_carry_promoted_at_recovery(self, tmp_path):
        """The open-window carry accepted before the crash is promoted
        by the recovered coordinator (the old incarnation's member is
        presumed dead) and merges exactly once next to the successor's
        replayed rows."""
        c = self.make(tmp_path)
        c.join("a")
        c.sync("a")
        c.submit("a", codec.encode(_contrib(
            {0: [0, 8]}, wm=100, open_={300: _wagg_win(2, 30)})))
        s2 = ListSink()
        c2 = self.make(tmp_path, sink=s2)
        # the old member is unknown to the new incarnation: zombie path
        assert c2.sync("a")["action"] == "rejoin"
        r = c2.submit("a", codec.encode(_contrib({0: [8, 9]}, wm=700)))
        assert not r["ok"] and r["reason"] == "fenced"
        c2.join("b")
        c2.sync("b")
        c2.submit("b", codec.encode(_contrib(
            {0: [8, 12]}, wm=700, closed={300: _wagg_win(2, 12)},
            final=True)))
        rows = c2.merged_rows("flows_5m", 300)
        assert len(rows) == 1
        assert int(rows[0]["bytes"][0]) == 42  # carry 30 + successor 12

    def test_second_crash_replays_identically(self, tmp_path):
        """Recovery journals its own fences, so a coordinator that
        crashes AGAIN after recovering does not double-promote the
        first incarnation's carries."""
        c = self.make(tmp_path)
        c.join("a")
        c.sync("a")
        c.submit("a", codec.encode(_contrib(
            {0: [0, 8]}, wm=100, open_={300: _wagg_win(2, 30)})))
        c2 = self.make(tmp_path)  # crash 1: promotes the carry
        s3 = ListSink()
        c3 = self.make(tmp_path, sink=s3)  # crash 2
        c3.join("b")
        c3.sync("b")
        c3.submit("b", codec.encode(_contrib(
            {0: [8, 12]}, wm=700, closed={300: _wagg_win(2, 12)},
            final=True)))
        rows = c3.merged_rows("flows_5m", 300)
        assert len(rows) == 1
        assert int(rows[0]["bytes"][0]) == 42  # 30 once, not twice
        assert c3.epoch > c2.epoch

    def test_resubmitted_range_rejected_harmlessly(self, tmp_path):
        """The idempotence pin: a retried submission whose ack was lost
        no longer extends the frontier — it is REJECTED (never applied
        twice), the member is fenced, and the rejoin/replay path keeps
        the merge exact."""
        c = self.make(tmp_path)
        c.join("a")
        c.sync("a")
        payload = codec.encode(_contrib(
            {0: [0, 10]}, wm=100, open_={300: _wagg_win(5, 20)}))
        assert c.submit("a", payload)["ok"]
        # the retry of the SAME envelope (lost ack): rejected, frontier
        # and carry untouched
        r = c.submit("a", payload)
        assert not r["ok"] and r["reason"] == "range"
        assert c.status()["covered"] == [10]
        # the member rejoins fresh and replays from the frontier; its
        # carry was promoted by the rejection's fence
        assert c.sync("a")["action"] == "rejoin"
        c.join("a")
        c.sync("a")
        c.submit("a", codec.encode(_contrib(
            {0: [10, 12]}, wm=700, closed={300: _wagg_win(5, 7)},
            final=True)))
        rows = c.merged_rows("flows_5m", 300)
        assert len(rows) == 1
        assert int(rows[0]["bytes"][0]) == 27  # 20 once + 7, not 47

    def test_duplicate_empty_range_submission_acked_idempotently(
            self, tmp_path):
        """The case the frontier-extend check alone cannot catch: a
        final/idle-flush submission carries closed windows but NO new
        offsets (ranges [covered, covered]); its lost-ack retry passes
        the range check. The span.sub dedupe must ack it idempotently
        WITHOUT re-folding the windows (review finding: double-count)."""
        c = self.make(tmp_path)
        c.join("a")
        c.sync("a")
        # advance the frontier first
        assert c.submit("a", codec.encode(dict(
            _contrib({0: [0, 10]}, wm=100), span={"sub": 1})))["ok"]
        # idle-flush: closed window, empty range
        payload = codec.encode(dict(
            _contrib({0: [10, 10]}, wm=700,
                     closed={300: _wagg_win(4, 19)}),
            span={"sub": 2}))
        assert c.submit("a", payload)["ok"]
        r = c.submit("a", payload)  # lost-ack retry, same envelope
        assert r["ok"] and r.get("duplicate")
        # member stays live (no fence) and nothing folded twice
        assert c.sync("a")["action"] == "run"
        c.submit("a", codec.encode(dict(
            _contrib({0: [10, 11]}, wm=701, final=True),
            span={"sub": 3})))
        rows = c.merged_rows("flows_5m", 300)
        assert len(rows) == 1
        assert int(rows[0]["bytes"][0]) == 19  # once, not 38


# ---------------------------------------------------------------------------
# resilient sink: retry + dead-letter + replay
# ---------------------------------------------------------------------------


class _FlakySink:
    """Fails the first ``fail`` write attempts, then accepts."""

    def __init__(self, fail):
        self.fail = fail
        self.inner = MemorySink()
        self.attempts = 0

    def write(self, table, rows):
        self.attempts += 1
        if self.attempts <= self.fail:
            raise ConnectionResetError("transient sink blip")
        self.inner.write(table, rows)


class TestResilientSink:
    ROWS = [{"src_as": 1, "bytes": 10}, {"src_as": 2, "bytes": 20}]

    def test_transient_failure_retried(self):
        flaky = _FlakySink(fail=2)
        rs = ResilientSink(flaky, retries=4, backoff=0.001,
                           backoff_max=0.002, sleep=lambda _: None)
        rs.write("flows_5m", list(self.ROWS))
        assert flaky.inner.tables["flows_5m"] == self.ROWS
        assert flaky.attempts == 3

    def test_exhaustion_without_deadletter_reraises(self):
        rs = ResilientSink(_FlakySink(fail=99), retries=2, backoff=0.001,
                           sleep=lambda _: None)
        with pytest.raises(ConnectionResetError):
            rs.write("flows_5m", list(self.ROWS))

    def test_deterministic_bug_not_retried_or_spilled(self, tmp_path):
        """A schema/shape bug (ValueError & co.) must fail the step
        immediately: retrying triples its latency, and spilling it
        would park a poison file at the head of the dead-letter queue
        (replay stops at the first failure to preserve order)."""
        class Buggy:
            def __init__(self):
                self.attempts = 0

            def write(self, table, rows):
                self.attempts += 1
                raise ValueError("schema mismatch")

        buggy = Buggy()
        rs = ResilientSink(buggy, retries=4, backoff=0.001,
                           deadletter_dir=str(tmp_path),
                           sleep=lambda _: None)
        with pytest.raises(ValueError):
            rs.write("flows_5m", list(self.ROWS))
        assert buggy.attempts == 1  # no retries
        assert deadletter_files(str(tmp_path)) == []  # no poison spill

    def test_exhaustion_spills_and_replays(self, tmp_path):
        flaky = _FlakySink(fail=99)
        rs = ResilientSink(flaky, retries=2, backoff=0.001,
                           deadletter_dir=str(tmp_path),
                           sleep=lambda _: None)
        rs.write("flows_5m", list(self.ROWS))  # survives
        files = deadletter_files(str(tmp_path))
        assert len(files) == 1
        doc = json.loads(open(files[0]).read())
        assert doc["table"] == "flows_5m"
        assert doc["records"] == self.ROWS
        assert rs._m["depth"].value() == 1.0
        # replay into a healthy sink restores the rows and drains disk
        target = MemorySink()
        n_files, n_rows = replay_deadletter(str(tmp_path), [target])
        assert (n_files, n_rows) == (1, 2)
        assert target.tables["flows_5m"] == self.ROWS
        assert deadletter_files(str(tmp_path)) == []

    def test_replay_failure_keeps_files_in_order(self, tmp_path):
        rs = ResilientSink(_FlakySink(fail=99), retries=1,
                           deadletter_dir=str(tmp_path),
                           sleep=lambda _: None)
        rs.write("flows_5m", [{"src_as": 1}])
        rs.write("flows_5m", [{"src_as": 2}])
        dead = _FlakySink(fail=99)
        with pytest.raises(ConnectionResetError):
            replay_deadletter(str(tmp_path), [dead])
        assert len(deadletter_files(str(tmp_path))) == 2

    def test_restart_reports_inherited_backlog(self, tmp_path):
        rs = ResilientSink(_FlakySink(fail=99), retries=1,
                           deadletter_dir=str(tmp_path),
                           sleep=lambda _: None)
        rs.write("flows_5m", [{"src_as": 1}])
        rs2 = ResilientSink(MemorySink(), retries=1,
                            deadletter_dir=str(tmp_path))
        assert rs2._m["depth"].value() == 1.0

    def test_injected_faults_hit_the_seam(self, tmp_path):
        FAULTS.configure("sink.write:p=1@seed=1")
        inner = MemorySink()
        rs = ResilientSink(inner, retries=2, backoff=0.001,
                           deadletter_dir=str(tmp_path),
                           sleep=lambda _: None)
        rs.write("flows_5m", list(self.ROWS))
        FAULTS.configure(None)
        assert "flows_5m" not in inner.tables  # every attempt injected
        assert len(deadletter_files(str(tmp_path))) == 1

    def test_passthrough_surfaces(self):
        class Archiving(MemorySink):
            def archive_raw(self, batch):
                return 0

        rs = ResilientSink(Archiving())
        assert getattr(rs, "archive_raw", None) is not None
        assert getattr(rs, "check_raw_schema", None) is None


# ---------------------------------------------------------------------------
# e2e: sink fault leg — the worker survives, dead-letter + replay
# restore row-set equality with a fault-free run
# ---------------------------------------------------------------------------


def _records_key(rec):
    return json.dumps(rec, sort_keys=True, default=str)


def test_worker_survives_sink_faults_and_replay_restores_rows(tmp_path):
    # the multi-window stream: many window closes -> many sink writes,
    # so the seeded plan deterministically exhausts several batches
    # (both legs consume the IDENTICAL stream, so the row-set compare
    # is valid whatever the windowing)
    vals = _vals()
    cfg = WorkerConfig(poll_max=BATCH, snapshot_every=0)
    clean = MemorySink()
    StreamWorker(Consumer(_make_bus(rate=MULTIWIN_RATE), "flows",
                          fixedlen=True),
                 _build_models(vals), [clean], cfg).run(stop_when_idle=True)
    faulty = MemorySink()
    rs = ResilientSink(faulty, retries=2, backoff=0.0005,
                       backoff_max=0.001,
                       deadletter_dir=str(tmp_path))
    FAULTS.configure("sink.write:p=0.6@seed=11")
    worker = StreamWorker(Consumer(_make_bus(rate=MULTIWIN_RATE),
                                   "flows", fixedlen=True),
                          _build_models(vals), [rs], cfg)
    worker.run(stop_when_idle=True)  # must NOT raise FlushError
    FAULTS.configure(None)
    spilled = deadletter_files(str(tmp_path))
    assert spilled, "seeded plan produced no exhausted batches"
    # before replay the faulty sink is missing the spilled rows
    missing = sum(len(json.loads(open(f).read())["records"])
                  for f in spilled)
    assert missing > 0
    replay_deadletter(str(tmp_path), [faulty])
    assert deadletter_files(str(tmp_path)) == []
    assert set(clean.tables) == set(faulty.tables)
    for table in clean.tables:
        a = sorted(_records_key(r) for r in clean.tables[table])
        b = sorted(_records_key(r) for r in faulty.tables[table])
        assert a == b, f"row-set mismatch in {table}"


# ---------------------------------------------------------------------------
# e2e: kill the COORDINATOR mid-stream — journal recovery keeps the
# merged sink output bit-exact vs the single-worker oracle
# ---------------------------------------------------------------------------


class CrashableCoordinator:
    """The process boundary, simulated: while ``down``, every protocol
    call fails with the OSError a dead HTTP endpoint produces. The
    member-side retry machinery is what rides through."""

    def __init__(self, real):
        self.real = real
        self.down = threading.Event()

    def _check(self):
        if self.down.is_set():
            raise ConnectionRefusedError(
                "coordinator down (simulated crash)")

    def join(self, *a, **kw):
        self._check()
        return self.real.join(*a, **kw)

    def sync(self, *a, **kw):
        self._check()
        return self.real.sync(*a, **kw)

    def submit(self, *a, **kw):
        self._check()
        return self.real.submit(*a, **kw)

    def leave(self, *a, **kw):
        self._check()
        return self.real.leave(*a, **kw)


def test_kill_coordinator_mid_stream_recovers_bit_exact(tmp_path):
    """The headline acceptance leg: the coordinator dies mid-stream
    with accepted-but-unmerged state; a fresh incarnation recovers from
    its journal, fences the old members through the zombie/rejoin
    machinery, and the merged flows_5m + top-K sink rows stay bit-exact
    vs the single-worker oracle — no lost, no double-counted windows."""
    vals = _vals()
    sink1, sink2 = ListSink(), ListSink()
    _run_single_worker(vals, sink1)

    jdir = str(tmp_path / "wal")
    specs = spec_from_models(_build_models(vals))
    coord1 = MeshCoordinator(specs, PARTITIONS, sinks=[sink2],
                             journal=jdir)
    proxy = CrashableCoordinator(coord1)
    bus = _make_bus()
    config = WorkerConfig(poll_max=BATCH, snapshot_every=0)

    def consumer_factory(partitions):
        return Consumer(bus, "flows", group="chaos", fixedlen=True,
                        partitions=list(partitions))

    members = [
        MeshMember(f"w{i}", proxy, consumer_factory,
                   model_factory=lambda: _build_models(vals),
                   config=config, submit_every=2, sync_interval=0.01)
        for i in range(3)
    ]
    # DELTA, not absolute: the submit counter is process-global and
    # earlier mesh tests have already moved it
    submit0 = coord1._m["submit"].value()
    stop = threading.Event()
    threads = [threading.Thread(target=m.run, args=(stop,),
                                name=f"chaos-{m.member_id}", daemon=True)
               for m in members]
    for t in threads:
        t.start()
    # mid-stream: wait until real work is accepted (progress carries are
    # flowing, some windows may already have merged)
    deadline = time.time() + 120
    while time.time() < deadline:
        if coord1._m["submit"].value() - submit0 >= 6:
            break
        time.sleep(0.002)
    else:
        pytest.fail("coordinator never accepted enough submissions")

    # CRASH: the old incarnation's memory dies with it; only the
    # journal survives. Members see connection-refused and retry.
    proxy.down.set()
    coord2 = MeshCoordinator(specs, PARTITIONS, sinks=[sink2],
                             journal=jdir)
    assert coord2.epoch > 0
    proxy.real = coord2
    proxy.down.clear()

    # quiescence: every member idle AND the recovered coordinator owns
    # out the full partition set (rebalance settled after the rejoins)
    deadline = time.time() + 240
    streak = 0
    while time.time() < deadline:
        ok = all(m.idle_streak >= 20 for m in members)
        if ok:
            st = coord2.status()
            owned = sum(len(v["owned"]) for v in st["members"].values())
            ok = owned == st["partitions"]
        streak = streak + 1 if ok else 0
        if streak >= 2:
            break
        time.sleep(0.02)
    else:
        pytest.fail("mesh did not quiesce after coordinator recovery")
    stop.set()
    for t in threads:
        t.join(timeout=60)
    for m in members:
        m.finalize()
    coord2.close()

    _assert_flows5m_oracle_exact(sink2.tables)
    _assert_topk_tables_equal(sink1.tables, sink2.tables)
    # the recovery actually replayed journaled submissions (count the
    # records directly — the metric counter is process-global)
    kinds = [k for k, _, _ in
             replay_journal(os.path.join(jdir, "coordinator.journal"))]
    assert kinds.count("sub") >= 6
    assert "epoch" in kinds


def test_kill_coordinator_multiwindow_merged_windows_survive(tmp_path):
    """Multi-window variant: the stream crosses 5-minute boundaries, so
    windows MERGE (and journal ``merged`` records) before the crash.
    Recovery must re-emit none of them and still merge everything
    pending — the flows_5m fold stays exact vs the numpy oracle.
    (flows_5m only: its late-partial semantics are exact under any
    consumption order, which is what makes the oracle valid here —
    see the RATE comment above.)"""
    vals = _vals("-model.talkers=false")
    jdir = str(tmp_path / "wal")
    specs = spec_from_models(_build_models(vals))
    sink = ListSink()
    coord1 = MeshCoordinator(specs, PARTITIONS, sinks=[sink],
                             journal=jdir)
    proxy = CrashableCoordinator(coord1)
    bus = _make_bus(rate=MULTIWIN_RATE)
    config = WorkerConfig(poll_max=BATCH, snapshot_every=0)

    def consumer_factory(partitions):
        return Consumer(bus, "flows", group="chaos-mw", fixedlen=True,
                        partitions=list(partitions))

    members = [
        MeshMember(f"w{i}", proxy, consumer_factory,
                   model_factory=lambda: _build_models(vals),
                   config=config, submit_every=2, sync_interval=0.01)
        for i in range(3)
    ]
    # DELTA, not absolute: the merged counter is process-global
    merged0 = coord1._m["merged"].value(model="flows_5m")
    stop = threading.Event()
    threads = [threading.Thread(target=m.run, args=(stop,),
                                daemon=True) for m in members]
    for t in threads:
        t.start()
    # crash only after at least one window MERGED network-wide (its
    # `merged` journal record is what the recovery must honor)
    deadline = time.time() + 120
    while time.time() < deadline:
        if coord1._m["merged"].value(model="flows_5m") - merged0 >= 1:
            break
        time.sleep(0.002)
    else:
        pytest.fail("no window merged before the crash point")
    proxy.down.set()
    coord2 = MeshCoordinator(specs, PARTITIONS, sinks=[sink],
                             journal=jdir)
    proxy.real = coord2
    proxy.down.clear()
    deadline = time.time() + 240
    streak = 0
    while time.time() < deadline:
        ok = all(m.idle_streak >= 20 for m in members)
        if ok:
            st = coord2.status()
            owned = sum(len(v["owned"]) for v in st["members"].values())
            ok = owned == st["partitions"]
        streak = streak + 1 if ok else 0
        if streak >= 2:
            break
        time.sleep(0.02)
    else:
        pytest.fail("mesh did not quiesce after coordinator recovery")
    stop.set()
    for t in threads:
        t.join(timeout=60)
    for m in members:
        m.finalize()
    coord2.close()
    _assert_flows5m_oracle_exact(sink.tables, rate=MULTIWIN_RATE)


# ---------------------------------------------------------------------------
# e2e: chaos soak — seeded transport faults across the mesh edges,
# merged output stays oracle-exact
# ---------------------------------------------------------------------------


def test_chaos_soak_mesh_transport_faults_stay_oracle_exact():
    vals = _vals()
    sink1, sink2 = ListSink(), ListSink()
    _run_single_worker(vals, sink1)
    FAULTS.configure("mesh.submit:p=0.08;mesh.sync:p=0.05@seed=7")
    mesh = InProcessMesh(
        _make_bus(), "flows", 3,
        model_factory=lambda: _build_models(vals),
        config=WorkerConfig(poll_max=BATCH, snapshot_every=0),
        sinks=[sink2], submit_every=2)
    mesh.run()
    snap = FAULTS.snapshot()
    FAULTS.configure(None)
    assert sum(s["injected"] for s in snap.values()) > 0, \
        "soak injected nothing — the seams are not wired"
    _assert_flows5m_oracle_exact(sink2.tables)
    _assert_topk_tables_equal(sink1.tables, sink2.tables)


# ---------------------------------------------------------------------------
# serve publisher: failure-path rate limit + zero 5xx under faults
# ---------------------------------------------------------------------------


class TestServePublishFailurePath:
    def _publisher(self, **kw):
        from flow_pipeline_tpu.serve.publisher import MeshServePublisher

        coord = MeshCoordinator([_wagg_spec()], 1)
        return MeshServePublisher(coord, refresh=0.2,
                                  err_backoff_base=0.5,
                                  err_backoff_max=4.0,
                                  err_log_interval=30.0, **kw)

    def test_failure_counter_and_backoff_growth(self):
        pub = self._publisher()
        before = pub.store.m_publish_failures.value()
        delays = []
        for _ in range(6):
            pub._on_publish_error(RuntimeError("member fetch failed"))
            delays.append(pub._error_backoff())
        assert pub.store.m_publish_failures.value() == before + 6
        assert delays == sorted(delays)  # monotone growth
        assert delays[0] == 0.5 and delays[-1] == 4.0  # floored, capped
        pub._fail_streak = 0
        assert pub._error_backoff() == 0.5

    def test_exception_log_rate_limited(self):
        import logging

        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record)

        # the flowtpu root logger does not propagate; attach directly
        logger = logging.getLogger("flowtpu.serve")
        handler = Capture(level=logging.DEBUG)
        logger.addHandler(handler)
        old_level = logger.level
        logger.setLevel(logging.DEBUG)
        try:
            pub = self._publisher()
            for _ in range(10):
                pub._on_publish_error(RuntimeError("flap"))
        finally:
            logger.removeHandler(handler)
            logger.setLevel(old_level)
        errors = [r for r in records if r.levelno >= logging.ERROR]
        assert len(errors) == 1  # one traceback per err_log_interval
        assert pub.store.m_publish_failures.value() >= 10


def test_serve_zero_5xx_under_publish_faults():
    """Readers keep getting 2xx answers (the previous snapshot) while
    the mesh publisher's fan-out is failing under injected faults."""
    from flow_pipeline_tpu.serve import ServeServer
    from flow_pipeline_tpu.serve.publisher import MeshServePublisher

    vals = _vals()
    mesh = InProcessMesh(
        _make_bus(n_flows=8192), "flows", 2,
        model_factory=lambda: _build_models(vals),
        config=WorkerConfig(poll_max=BATCH, snapshot_every=0))
    pub = MeshServePublisher(mesh.coordinator, refresh=0.05,
                             err_backoff_base=0.05, err_backoff_max=0.2,
                             err_log_interval=60.0).attach()
    server = ServeServer(pub.store, 0).start()
    pub.start()
    mesh.start()
    codes = []
    versions = []
    try:
        deadline = time.time() + 30
        while pub.store.current is None and time.time() < deadline:
            time.sleep(0.01)
        assert pub.store.current is not None
        FAULTS.configure("serve.publish:p=0.5@seed=3")

        def read(path):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}{path}")
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    body = resp.read()
                    codes.append(resp.status)
                    if path == "/query/version":
                        versions.append(json.loads(body)["version"])
            except urllib.error.HTTPError as e:
                codes.append(e.code)

        t_end = time.time() + 1.5
        while time.time() < t_end:
            read("/query/version")
            read("/query/topk?k=5")
    finally:
        FAULTS.configure(None)
        try:
            mesh.wait_idle()
        finally:
            mesh.finalize()
            pub.stop()
            server.stop()
    assert codes and all(c < 500 for c in codes), codes
    assert versions == sorted(versions)  # monotone under failures
    assert pub.store.m_publish_failures.value() > 0


# ---------------------------------------------------------------------------
# r18 satellites: journal compaction + in-process-bus / gateway fault seams
# ---------------------------------------------------------------------------


class TestJournalCompaction:
    """r17's named follow-on: at merged-window boundaries the WAL drops
    superseded carry envelopes and checkpoints+truncates. The gate is
    bit-exactness: recovery from a compacted journal must equal
    recovery from the uncompacted replay — same frontier, same pending
    barrier, same carries, same merged-window keys, same sink rows."""

    def _drive(self, path, compact=False, sink=None):
        c = MeshCoordinator([_wagg_spec()], 1,
                            sinks=[sink] if sink else (),
                            journal=path)
        c.join("m")
        c.sync("m")
        c.submit("m", codec.encode(_contrib(
            {0: [0, 10]}, wm=900, closed={300: _wagg_win(7, 50)})))
        c.submit("m", codec.encode(_contrib(
            {0: [10, 20]}, wm=950, open_={600: _wagg_win(3, 9)})))
        c.submit("m", codec.encode(_contrib(
            {0: [20, 25]}, wm=980, closed={600: _wagg_win(3, 2)},
            open_={900: _wagg_win(4, 5)})))
        if compact:
            assert c.compact_journal()
        c.close()
        return c

    @staticmethod
    def _protocol_state(c):
        st = c.status()
        return {k: st[k] for k in ("covered", "watermarks", "final",
                                   "pending_windows")}

    def test_recovery_after_compaction_bit_exact_vs_uncompacted(
            self, tmp_path):
        self._drive(str(tmp_path / "a"), compact=True)
        self._drive(str(tmp_path / "b"), compact=False)
        sa, sb = ListSink(), ListSink()
        ra = MeshCoordinator([_wagg_spec()], 1, sinks=[sa],
                             journal=str(tmp_path / "a"))
        rb = MeshCoordinator([_wagg_spec()], 1, sinks=[sb],
                             journal=str(tmp_path / "b"))
        assert self._protocol_state(ra) == self._protocol_state(rb)
        assert ra._merged_keys == rb._merged_keys
        assert sorted(ra._carry) == sorted(rb._carry)
        # drive both recovered coordinators to completion identically:
        # the pending window and the recovered carries must merge to
        # bit-identical sink rows
        for c in (ra, rb):
            c.join("n")
            c.sync("n")
            c.submit("n", codec.encode(_contrib(
                {0: [25, 30]}, wm=2000, closed={900: _wagg_win(4, 1)},
                final=True)))
        assert set(sa.tables) == set(sb.tables) and sa.tables
        for table in sa.tables:
            wa = [{k: np.asarray(v).tolist() for k, v in r.items()}
                  for r in sa.tables[table]]
            wb = [{k: np.asarray(v).tolist() for k, v in r.items()}
                  for r in sb.tables[table]]
            assert wa == wb

    def test_compaction_drops_superseded_envelopes(self, tmp_path):
        """The 379MB-for-35-records lever: after compaction the file
        holds ONE chk record (+ later appends), and its size is a
        fraction of the replaced history's."""
        c = self._drive(str(tmp_path / "wal"), compact=False)
        big = c._journal.size_bytes()
        c2 = MeshCoordinator([_wagg_spec()], 1,
                             journal=str(tmp_path / "wal"))
        pre = c2._journal.size_bytes()
        assert c2.compact_journal()
        post = c2._journal.size_bytes()
        assert post < pre and post < big
        kinds = [k for k, _, _ in replay_journal(
            str(tmp_path / "wal" / "coordinator.journal"))]
        assert kinds[0] == "chk"
        # recovery fences journaled during c2's own recovery are gone:
        # the checkpoint absorbed them
        assert "sub" not in kinds
        c2.close()

    def test_compaction_defers_while_a_merge_is_in_flight(self, tmp_path):
        """The checkpoint races the lock-free merge path: a window
        popped off the barrier is in _merged_keys BEFORE its rows reach
        any sink or its "merged" record the WAL. A checkpoint taken in
        that gap would record it merged while truncating the sub
        records recovery needs to re-merge it — a crash then loses the
        window silently. compact_journal() must defer until the merge
        lands (the size trigger simply fires at the next boundary)."""
        gate_enter, gate_release = threading.Event(), threading.Event()

        class GateSink:
            def __init__(self):
                self.tables = {}

            def write(self, table, rows):
                gate_enter.set()
                assert gate_release.wait(10)
                self.tables.setdefault(table, []).append(rows)

        sink = GateSink()
        c = MeshCoordinator([_wagg_spec()], 1, sinks=[sink],
                            journal=str(tmp_path / "wal"))
        c.join("m")
        c.sync("m")
        t = threading.Thread(target=c.submit, args=("m", codec.encode(
            _contrib({0: [0, 10]}, wm=900,
                     closed={300: _wagg_win(7, 50)}))))
        t.start()
        assert gate_enter.wait(10)  # popped off the barrier, mid-emit
        try:
            assert not c.compact_journal()  # deferred: merge in flight
        finally:
            gate_release.set()
            t.join(10)
        assert c.compact_journal()  # landed -> checkpoint is safe now
        c.close()
        # the deferral lost nothing: recovery from the checkpoint still
        # knows the window merged (its rows reached the sink first)
        r = MeshCoordinator([_wagg_spec()], 1,
                            journal=str(tmp_path / "wal"))
        assert ("flows_5m", 300) in r._merged_keys
        r.close()

    def test_records_after_checkpoint_replay_on_top(self, tmp_path):
        sink = ListSink()
        c = self._drive(str(tmp_path / "wal"), compact=True, sink=sink)
        # reopen, accept MORE submissions after the checkpoint
        c2 = MeshCoordinator([_wagg_spec()], 1,
                             journal=str(tmp_path / "wal"))
        c2.join("n")
        c2.sync("n")
        c2.submit("n", codec.encode(_contrib(
            {0: [25, 40]}, wm=1000, open_={900: _wagg_win(4, 6)})))
        c2.close()
        # crash again: chk + post-checkpoint subs both replay
        c3 = MeshCoordinator([_wagg_spec()], 1,
                             journal=str(tmp_path / "wal"))
        assert c3.status()["covered"] == [40]
        # both incarnations' carries were promoted into pending
        assert "flows_5m:900" in c3.status()["pending_windows"]

    def test_mesh_journal_bytes_gauge_tracks_the_file(self, tmp_path):
        c = MeshCoordinator([_wagg_spec()], 1,
                            journal=str(tmp_path / "wal"))
        g0 = c._m["journal_bytes"].value()
        assert g0 > 0  # magic written eagerly
        c.join("m")
        c.sync("m")
        c.submit("m", codec.encode(_contrib(
            {0: [0, 5]}, wm=100, open_={300: _wagg_win(1, 1)})))
        grown = c._m["journal_bytes"].value()
        assert grown > g0
        chk0 = c._m["journal_records"].value(kind="chk")
        assert c.compact_journal()
        # the gauge is the file: flush + compare against the on-disk
        # truth (a tiny history can legitimately checkpoint BIGGER —
        # the shrink claim lives in test_compaction_drops_superseded_
        # envelopes where the history dominates)
        c._journal.sync()
        assert c._m["journal_bytes"].value() == os.path.getsize(
            str(tmp_path / "wal" / "coordinator.journal"))
        # DELTA, not absolute: the counter is process-global (the r17
        # wait-condition lesson, re-applied)
        assert c._m["journal_records"].value(kind="chk") == chk0 + 1.0
        c.close()

    def test_auto_compaction_at_merged_window_boundary(self, tmp_path):
        """The trigger rides _run_merges: once the WAL crosses
        journal_compact_bytes, the next merged-window boundary
        compacts without anyone calling compact_journal()."""
        c = MeshCoordinator([_wagg_spec()], 1,
                            journal=str(tmp_path / "wal"),
                            journal_compact_bytes=1)  # always over
        c.join("m")
        c.sync("m")
        # wm past the barrier: merges (and therefore compacts) NOW
        c.submit("m", codec.encode(_contrib(
            {0: [0, 10]}, wm=900, closed={300: _wagg_win(7, 50)})))
        kinds = [k for k, _, _ in replay_journal(
            str(tmp_path / "wal" / "coordinator.journal"))]
        assert "chk" in kinds
        # recovery still lands on the merged state (nothing re-emits)
        s2 = ListSink()
        c2 = MeshCoordinator([_wagg_spec()], 1, sinks=[s2],
                             journal=str(tmp_path / "wal"))
        assert "flows_5m" not in s2.tables  # merged pre-crash: no re-emit
        assert c2.status()["covered"] == [10]
        c.close()
        c2.close()


class TestBusAndGatewayFaultSeams:
    """r17's other named follow-on: collector-side chaos is now
    expressible — the in-process bus produce/poll paths and the
    flowgate subscription poll consult the fault plan."""

    def test_new_sites_are_known(self):
        sites, _ = parse_plan(
            "bus.produce:p=0.1;bus.poll:p=0.1;gateway.poll:p=0.1")
        assert set(sites) == {"bus.produce", "bus.poll", "gateway.poll"}

    def test_unknown_site_still_rejected(self):
        with pytest.raises(ValueError):
            parse_plan("bus.nope:p=0.1")

    def test_bus_produce_seam_fires(self):
        bus = InProcessBus()
        bus.create_topic("t", 1)
        FAULTS.configure("bus.produce:p=1@seed=3")
        with pytest.raises(OSError):
            bus.produce("t", b"x")
        with pytest.raises(OSError):
            bus.produce_many("t", [b"x", b"y"])
        FAULTS.configure(None)
        bus.produce("t", b"x")
        assert FAULTS.active is False

    def test_bus_poll_seam_fires(self):
        bus = InProcessBus()
        bus.create_topic("t", 1)
        bus.produce("t", b"x")
        FAULTS.configure("bus.poll:p=1@seed=3")
        with pytest.raises(OSError):
            bus.fetch("t", 0, 0)
        with pytest.raises(OSError):
            bus.fetch_span("t", 0, 0)
        FAULTS.configure(None)
        assert len(bus.fetch("t", 0, 0)) == 1

    def test_off_mode_bus_cost_is_one_attribute_read(self):
        bus = InProcessBus()
        bus.create_topic("t", 1)
        FAULTS.configure(None)
        bus.produce("t", b"x")  # no roll consumed
        assert FAULTS.snapshot() == {}

    def test_gateway_poll_seam_drives_the_real_failure_path(self):
        """The injected gateway.poll fault rides the SAME OSError path
        a dead upstream does: the mirror keeps its snapshot and
        recovers when the plan disarms (tests/test_gateway.py has the
        serving-side chaos leg)."""
        from flow_pipeline_tpu.gateway import SnapshotGateway
        from flow_pipeline_tpu.serve import SnapshotStore

        store = SnapshotStore()
        gw = SnapshotGateway([store], poll=60)
        FAULTS.configure("gateway.poll:p=1@seed=1")
        with pytest.raises(OSError):
            gw.sync_once()
        assert FAULTS.snapshot()["gateway.poll"]["injected"] >= 1
        FAULTS.configure(None)
        assert gw.sync_once() == "none"  # empty upstream, healthy poll
