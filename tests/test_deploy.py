"""Deploy artifact sanity: YAML/JSON validity, topology shape parity with
the reference (topic `flows`, 2 partitions, restart policies, Grafana
provisioning paths), and dashboard queries referencing real tables."""

import json
import os

import pytest

yaml = pytest.importorskip("yaml")

DEPLOY = os.path.join(os.path.dirname(__file__), "..", "deploy")

COMPOSE_FILES = [
    "compose/postgres-mock.yml",
    "compose/postgres-collect.yml",
    "compose/clickhouse-mock.yml",
    "compose/clickhouse-collect.yml",
]


def load(path):
    with open(os.path.join(DEPLOY, path)) as f:
        return yaml.safe_load(f)


class TestCompose:
    @pytest.mark.parametrize("path", COMPOSE_FILES)
    def test_valid_yaml_with_processor(self, path):
        doc = load(path)
        assert "processor" in doc["services"]
        cmd = doc["services"]["processor"]["command"]
        assert "flowtpu-processor" in cmd
        assert "-metrics.addr" in cmd

    @pytest.mark.parametrize("path", COMPOSE_FILES)
    def test_topic_two_partitions(self, path):
        # reference default: topic flows, 2 partitions, RF 1
        doc = load(path)
        init = doc["services"]["kafka-init"]["command"]
        assert "--topic flows" in init
        assert "--partitions 2" in init
        assert "--replication-factor 1" in init

    @pytest.mark.parametrize("path", COMPOSE_FILES)
    def test_long_running_services_restart(self, path):
        doc = load(path)
        for name, svc in doc["services"].items():
            if name == "kafka-init":
                continue
            assert svc.get("restart") == "always", name

    def test_collect_topologies_expose_flow_ports(self):
        for path in ("compose/postgres-collect.yml",
                     "compose/clickhouse-collect.yml"):
            doc = load(path)
            ports = doc["services"]["goflow"]["ports"]
            assert any("6343" in p for p in ports)  # sFlow
            assert any("2055" in p for p in ports)  # NetFlow/IPFIX

    def test_services_test_compose_is_backing_services_only(self):
        # `make services-test` composes THIS file then runs the suite
        # in-process: backing services with healthchecks (for --wait) and
        # localhost ports matching the CI services job's env contract
        doc = load("compose/services-test.yml")
        assert set(doc["services"]) == {"kafka", "postgres", "clickhouse"}
        for name, svc in doc["services"].items():
            assert "healthcheck" in svc, name
        assert any("9092" in p for p in doc["services"]["kafka"]["ports"])
        assert any("5432" in p for p in doc["services"]["postgres"]["ports"])
        assert any("8123" in p
                   for p in doc["services"]["clickhouse"]["ports"])

    def test_mesh_topology_shape(self):
        """mesh.yml: coordinator + 4 workers + sharded generator over an
        8-partition topic (2 partitions per worker — a death rebalances
        real sets); every worker names the coordinator, its own member
        id, and explicit-partition Kafka consumption; the mocker
        produces key-hash sharded."""
        doc = load("compose/mesh.yml")
        services = doc["services"]
        workers = [n for n in services if n.startswith("worker-")]
        assert len(workers) == 4
        assert "coordinator" in services and "mocker" in services
        init = services["kafka-init"]["command"]
        assert "--topic flows" in init and "--partitions 8" in init
        coord = services["coordinator"]["command"]
        assert "-mesh.role coordinator" in coord
        assert "-bus.partitions 8" in coord
        assert "-query.addr" in coord  # the mesh-aware /topk surface
        # flowchaos: restart:always + the write-ahead journal on the
        # durable volume = a crashed coordinator container actually
        # recovers its frontier/epoch/ledger (docs/FAULT_TOLERANCE.md)
        assert "-mesh.journal /data/journal" in coord
        assert "-sink.deadletter /data/spill" in coord
        assert "meshdata:/data" in services["coordinator"]["volumes"]
        # flowserve: the merged-snapshot read surface (lock-free /query/*)
        assert "-serve.addr" in coord
        assert any("8083" in p for p in
                   services["coordinator"]["ports"])
        for w in workers:
            cmd = services[w]["command"]
            assert "-mesh.role member" in cmd
            assert f"-mesh.id {w}" in cmd
            assert "-mesh.coordinator http://coordinator:8090" in cmd
            assert "-sketch.backend host" in cmd  # fused host dataplane
        mock = services["mocker"]["command"]
        assert "-produce.shard" in mock and "-bus.partitions 8" in mock
        for name, svc in services.items():
            if name != "kafka-init":
                assert svc.get("restart") == "always", name

    def test_mesh_topology_has_liveness_healthchecks(self):
        """meshscope satellite: the coordinator and every worker declare
        real /healthz healthchecks (the smoke driver previously had to
        infer liveness from /state content). The coordinator's probes
        its protocol port; workers probe their MetricsServer."""
        doc = load("compose/mesh.yml")
        services = doc["services"]
        coord_hc = services["coordinator"]["healthcheck"]["test"]
        assert "8090/healthz" in " ".join(coord_hc)
        for w in (n for n in services if n.startswith("worker-")):
            hc = services[w]["healthcheck"]["test"]
            assert "8081/healthz" in " ".join(hc), w

    def test_fixedlen_on_clickhouse_paths(self):
        for path in ("compose/clickhouse-mock.yml",
                     "compose/clickhouse-collect.yml"):
            doc = load(path)
            producers = [
                s for n, s in doc["services"].items()
                if n in ("mocker", "goflow")
            ]
            assert any("fixedlen" in p["command"] for p in producers)

    def test_clickhouse_grafana_has_plugin_and_ch_dashboards(self):
        for path in ("compose/clickhouse-mock.yml",
                     "compose/clickhouse-collect.yml"):
            doc = load(path)
            graf = doc["services"]["grafana"]
            assert graf["environment"]["GF_INSTALL_PLUGINS"] == (
                "grafana-clickhouse-datasource"
            )
            vols = "\n".join(graf["volumes"])
            assert "dashboards-ch/traffic.json" in vols
            assert "dashboards/pipeline.json" in vols
            # every topology has prometheus for the pipeline dashboard
            assert "prometheus" in doc["services"]

    def test_postgres_processor_gets_password_env(self):
        for path in ("compose/postgres-mock.yml",
                     "compose/postgres-collect.yml"):
            doc = load(path)
            proc = doc["services"]["processor"]
            assert "POSTGRES_PASSWORD" in proc["environment"]

    def test_ch_dashboard_parses_and_uses_ch_datasource(self):
        with open(os.path.join(DEPLOY, "grafana", "dashboards-ch",
                               "traffic.json")) as f:
            dash = json.load(f)
        assert all(p["datasource"] == "ClickHouse" for p in dash["panels"])


class TestPrometheus:
    def test_scrapes_processor(self):
        doc = load("prometheus/prometheus.yml")
        targets = [
            t
            for job in doc["scrape_configs"]
            for sc in job["static_configs"]
            for t in sc["targets"]
        ]
        assert "processor:8081" in targets  # the reference never scraped :8081


class TestGrafana:
    def test_collector_dashboard_uses_collector_metrics(self):
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "collector.json")) as f:
            text = f.read()
        # the GoFlow-shaped surface (SURVEY §2-C12) our collector exports
        for metric in ("udp_traffic_bytes", "flow_traffic_bytes",
                       "flow_process_nf_flowset_records_sum",
                       "flow_process_sf_samples_sum",
                       "flow_process_nf_errors_count",
                       "flow_process_nf_templates_count",
                       "flow_summary_decoding_time_us", "flow_decoder_count"):
            assert metric in text

    def test_dashboards_parse_and_reference_real_tables(self):
        for name in ("traffic.json", "pipeline.json", "collector.json"):
            with open(os.path.join(DEPLOY, "grafana", "dashboards", name)) as f:
                dash = json.load(f)
            assert dash["panels"]
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "traffic.json")) as f:
            text = f.read()
        from flow_pipeline_tpu.sink.ddl import SQLITE_TABLES

        for table in ("flows_5m", "top_talkers", "ddos_alerts"):
            assert table in text
            assert table in SQLITE_TABLES

    def test_collector_dashboard_depth(self):
        """Round-8 depth growth (VERDICT Missing #1): per-router delay
        quantiles, per-agent sFlow record rate, per-protocol decode time
        — on the exporter labels the collector already exports. 18
        panels and counting toward the reference perfs.json's 27."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "collector.json")) as f:
            dash = json.load(f)
        assert len(dash["panels"]) >= 18
        titles = {p["title"] for p in dash["panels"]}
        for want in ("Export delay by router (p50)",
                     "Export delay by router (p99)",
                     "sFlow record rate by agent",
                     "Decode time by protocol (us)"):
            assert want in titles, want
        exprs = [t.get("expr", "") for p in dash["panels"]
                 for t in p.get("targets", [])]
        # the delay quantile panels must slice the labeled summary series
        assert any('router!=""' in e and 'quantile="0.5"' in e
                   and "delay" in e for e in exprs)
        assert any('router!=""' in e and 'quantile="0.99"' in e
                   and "delay" in e for e in exprs)
        assert any('agent!=""' in e and "sf_samples" in e for e in exprs)

    def test_pipeline_dashboard_uses_exported_metrics(self):
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            text = f.read()
        for metric in ("flows_processed_total", "insert_count",
                       "consumer_lag", "flow_processing_time_us"):
            assert metric in text

    def test_pipeline_dashboard_flowtrace_panels(self):
        """Round-11 flowtrace panels: the host_fused in-kernel phase
        breakdown (the attribution fusion erased) and the stage-latency
        histogram heatmap (aggregable le buckets, not summary
        quantiles), plus the commit watermark."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            dash = json.load(f)
        panels = {p["title"]: p for p in dash["panels"]}
        breakdown = panels[
            "host_fused phase breakdown (in-kernel, ns/s)"]
        assert "host_fused_phase_ns_total" in \
            breakdown["targets"][0]["expr"]
        assert breakdown["targets"][0]["legendFormat"] == "{{phase}}"
        heat = panels["Stage latency heatmap (us, cumulative le buckets)"]
        assert heat["type"] == "heatmap"
        assert "flow_stage_duration_us_bucket" in \
            heat["targets"][0]["expr"]
        assert "by (le)" in heat["targets"][0]["expr"]
        wm = panels["Sink commit watermark lag (s)"]
        exprs = " ".join(t["expr"] for t in wm["targets"])
        assert "flow_commit_watermark_seconds" in exprs
        assert "flow_sink_commit_latency_seconds_bucket" in exprs

    def test_pipeline_dashboard_mesh_panels(self):
        """Round-12 flowmesh panels: per-worker ingest rate (by the
        member label), merge wall time off the aggregable histogram
        buckets, and rebalance events by reason next to the live
        membership/epoch gauges."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            dash = json.load(f)
        panels = {p["title"]: p for p in dash["panels"]}
        ingest = panels["Mesh per-worker ingest rate (flows/s)"]
        assert "mesh_member_flows_total" in ingest["targets"][0]["expr"]
        assert ingest["targets"][0]["legendFormat"] == "{{member}}"
        merge = panels["Mesh window merge wall time (s)"]
        exprs = " ".join(t["expr"] for t in merge["targets"])
        assert "mesh_merge_seconds_bucket" in exprs
        assert "by (le)" in exprs
        assert "mesh_windows_merged_total" in exprs
        reb = panels["Mesh rebalance events"]
        exprs = " ".join(t["expr"] for t in reb["targets"])
        assert "mesh_rebalance_total" in exprs
        assert "mesh_members" in exprs and "mesh_epoch" in exprs

    def test_pipeline_dashboard_meshscope_panels(self):
        """Round-13 meshscope panels: per-member watermark skew (the
        stalled-shard signal), barrier-wait p99 off the aggregable
        buckets, and the lineage-derived submit->merge latency next to
        the rebalance-duration p99."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            dash = json.load(f)
        panels = {p["title"]: p for p in dash["panels"]}
        skew = panels["Mesh watermark skew by member (s)"]
        exprs = " ".join(t["expr"] for t in skew["targets"])
        assert "mesh_watermark_skew_seconds" in exprs
        assert "mesh_commit_watermark_seconds" in exprs
        assert skew["targets"][0]["legendFormat"] == "{{member}}"
        barrier = panels["Mesh barrier wait p99 (s)"]
        exprs = " ".join(t["expr"] for t in barrier["targets"])
        assert "mesh_barrier_wait_seconds_bucket" in exprs
        assert "histogram_quantile(0.99" in exprs and "by (le)" in exprs
        lat = panels["Mesh submit→merge latency (lineage, s)"]
        exprs = " ".join(t["expr"] for t in lat["targets"])
        assert "mesh_submit_to_merge_seconds_bucket" in exprs
        assert "mesh_rebalance_duration_seconds_bucket" in exprs
        assert "mesh_submit_total" in exprs

    def test_pipeline_dashboard_serve_panels(self):
        """Round-14 flowserve panels: query rate by endpoint, query
        latency quantiles off the aggregable le buckets, and snapshot
        age/freshness (live age from the publish timestamp, plus the
        publish rate)."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            dash = json.load(f)
        panels = {p["title"]: p for p in dash["panels"]}
        rate = panels["Serve query rate (req/s)"]
        exprs = " ".join(t["expr"] for t in rate["targets"])
        assert "serve_queries_total" in exprs
        assert "serve_cache_hits_total" in exprs
        assert rate["targets"][0]["legendFormat"] == "{{endpoint}}"
        lat = panels["Serve query latency p99 (s)"]
        exprs = " ".join(t["expr"] for t in lat["targets"])
        assert "serve_query_seconds_bucket" in exprs
        assert "histogram_quantile(0.99" in exprs and "by (le)" in exprs
        age = panels["Serve snapshot age (s)"]
        exprs = " ".join(t["expr"] for t in age["targets"])
        assert "serve_snapshot_timestamp_seconds" in exprs
        assert "serve_snapshot_age_seconds" in exprs
        assert "serve_snapshots_published_total" in exprs

    def test_pipeline_dashboard_gateway_panels(self):
        """Round-18 flowgate panels: subscription sync rate/bytes by
        coding kind with the resync rate (a climbing resync rate means
        the delta chain keeps breaking), and mirror freshness (upstream
        version minus served version) next to the pre-render rate and
        poll health."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            dash = json.load(f)
        panels = {p["title"]: p for p in dash["panels"]}
        sub = panels["Gateway subscription (delta vs full rate, "
                     "resyncs)"]
        exprs = " ".join(t["expr"] for t in sub["targets"])
        assert "gateway_syncs_total" in exprs
        assert "gateway_sync_bytes_total" in exprs
        assert "gateway_resyncs_total" in exprs
        fresh = panels["Gateway freshness (mirror lag, pre-render, "
                       "poll health)"]
        exprs = " ".join(t["expr"] for t in fresh["targets"])
        assert "gateway_upstream_version" in exprs
        assert "serve_snapshot_version" in exprs
        assert "gateway_prerendered_total" in exprs
        assert "gateway_poll_failures_total" in exprs

    def test_pipeline_dashboard_flowguard_panels(self):
        """Round-20 flowguard panels: the degradation-ladder level next
        to the shed rate by stage/reason (shedding is never silent),
        and the bounded-buffer occupancy charted against the watermark
        lag that drives the ladder."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            dash = json.load(f)
        panels = {p["title"]: p for p in dash["panels"]}
        level = panels["Flowguard level and shed rate"]
        exprs = " ".join(t["expr"] for t in level["targets"])
        assert "flow_guard_level" in exprs
        assert "guard_shed_total" in exprs
        assert "guard_transitions_total" in exprs
        legends = " ".join(t["legendFormat"] for t in level["targets"])
        assert "{{stage}}" in legends and "{{reason}}" in legends
        buf = panels["Flowguard buffers vs watermark lag"]
        exprs = " ".join(t["expr"] for t in buf["targets"])
        assert "guard_buffer_bytes" in exprs
        assert "flow_guard_lag_seconds" in exprs
        assert "faults_delayed_total" in exprs

    def test_pipeline_dashboard_flowspread_panels(self):
        """Round-21 flowspread panels: the per-detector max-distinct
        gauge (the alerting surface), the entropy anomaly signal
        charted against its EW baseline, and the sampled
        exact-distinct shadow audit's error/cohort health."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            dash = json.load(f)
        panels = {p["title"]: p for p in dash["panels"]}
        top = panels["Spread detectors (max distinct per window)"]
        exprs = " ".join(t["expr"] for t in top["targets"])
        assert "spread_top_max" in exprs
        assert "sketch_spread_audit_windows_total" in exprs
        assert top["targets"][0]["legendFormat"].startswith("{{model}}")
        ent = panels["Flow entropy vs baseline (DDoS collapse signal)"]
        exprs = " ".join(t["expr"] for t in ent["targets"])
        assert "flow_entropy" in exprs
        assert "flow_entropy_baseline" in exprs
        err = panels["Spread audit error (sampled exact-distinct "
                     "shadow)"]
        exprs = " ".join(t["expr"] for t in err["targets"])
        assert "sketch_spread_error_ratio_bucket" in exprs
        assert "histogram_quantile(0.99" in exprs and "by (le)" in exprs
        assert "sketch_spread_audit_sampled_keys" in exprs
        assert "sketch_spread_audit_cohort_overflow_total" in exprs

    def test_pipeline_dashboard_flowhistory_panels(self):
        """Round-22 flowhistory panels: archive write health (record
        rate by kind, on-disk bytes after retention, eviction rate)
        next to the read side (reconstruction p99 latency and chain
        depth, archive lag, gap 404s and damage skips — the honesty
        surface)."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            dash = json.load(f)
        panels = {p["title"]: p for p in dash["panels"]}
        arch = panels["Flowhistory archive (record rate, bytes, "
                      "eviction)"]
        exprs = " ".join(t["expr"] for t in arch["targets"])
        assert "history_records_total" in exprs
        assert "history_record_bytes_total" in exprs
        assert "history_archive_bytes" in exprs
        assert "history_evicted_segments_total" in exprs
        legends = " ".join(t["legendFormat"] for t in arch["targets"])
        assert "{{kind}}" in legends  # key vs delta split
        rec = panels["Flowhistory reconstruction (latency, depth, "
                     "gaps)"]
        exprs = " ".join(t["expr"] for t in rec["targets"])
        assert "history_reconstruct_seconds_bucket" in exprs
        assert "history_reconstruct_depth_bucket" in exprs
        assert "histogram_quantile(0.99" in exprs and "by (le)" in exprs
        assert "history_lag_versions" in exprs
        assert "history_gap_answers_total" in exprs
        assert "history_damage_skipped_total" in exprs

    def test_mesh_topology_history_tier(self):
        """Round-22 flowhistory compose: one archiver/time-travel
        service subscribed to the coordinator's snapshot feed, its
        segment archive on a durable named volume (restart:always +
        fsync discipline = a crash recovers into a fresh keyframe
        segment), with a real /healthz healthcheck."""
        doc = load("compose/mesh.yml")
        services = doc["services"]
        svc = services["history"]
        cmd = svc["command"]
        assert "flowtpu-history" in cmd
        assert "-history.upstream coordinator:8083" in cmd
        assert "-history.dir /data/history" in cmd
        assert "-history.listen" in cmd
        assert svc.get("restart") == "always"
        assert any(v.endswith(":/data") for v in svc["volumes"])
        assert "8086/healthz" in " ".join(svc["healthcheck"]["test"])

    def test_mesh_topology_gateway_tier(self):
        """Round-18 flowgate compose: two stateless gateway replicas
        front the coordinator's snapshot stream (the '2 gateways over
        the 4-worker mesh' read-tier topology), each with a real
        /healthz healthcheck."""
        doc = load("compose/mesh.yml")
        services = doc["services"]
        gateways = [n for n in services if n.startswith("gateway-")]
        assert len(gateways) == 2
        for g in gateways:
            svc = services[g]
            cmd = svc["command"]
            assert "flowtpu-gateway" in cmd
            assert "-gateway.upstream coordinator:8083" in cmd
            assert "-gateway.listen" in cmd
            assert svc.get("restart") == "always"
            hc = svc["healthcheck"]["test"]
            assert "8084/healthz" in " ".join(hc), g

    def test_pipeline_dashboard_sketchwatch_panels(self):
        """Round-15 sketchwatch panels: the sampled-audit error ratio
        off the aggregable le buckets, CMS fill / table occupancy and
        churn (the why behind error growth), and the sampled
        recall/precision next to the cohort-health panel."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            dash = json.load(f)
        panels = {p["title"]: p for p in dash["panels"]}
        err = panels["Sketch estimate error ratio (sampled audit)"]
        exprs = " ".join(t["expr"] for t in err["targets"])
        assert "sketch_estimate_error_ratio_bucket" in exprs
        assert "histogram_quantile(0.99" in exprs and "by (le)" in exprs
        assert 'path="cms"' in exprs and 'path="table"' in exprs
        fill = panels["Sketch CMS fill ratio (saturation)"]
        exprs = " ".join(t["expr"] for t in fill["targets"])
        assert "sketch_cms_fill_ratio" in exprs
        assert "sketch_cms_row_load_max" in exprs
        occ = panels["Sketch table occupancy and admission churn"]
        exprs = " ".join(t["expr"] for t in occ["targets"])
        assert "sketch_table_occupancy" in exprs
        assert "sketch_table_evictions_total" in exprs
        assert "sketch_table_est_admitted_fraction" in exprs
        rec = panels["Sketch heavy-hitter recall/precision "
                     "(sampled ground truth)"]
        exprs = " ".join(t["expr"] for t in rec["targets"])
        assert "sketch_hh_recall" in exprs
        assert "sketch_hh_precision" in exprs
        assert "sketch_audit_false_drop_total" in exprs
        cohort = panels["Sketch audit cohort (size, cadence, overflow)"]
        exprs = " ".join(t["expr"] for t in cohort["targets"])
        assert "sketch_audit_sampled_keys" in exprs
        assert "sketch_audit_cohort_overflow_total" in exprs

    def test_pipeline_dashboard_flowchaos_panels(self):
        """Round-17 flowchaos panels: sink retry/dead-letter rates, the
        dead-letter backlog depth next to the mesh transport retries
        and injected-fault rate, and the coordinator journal's WAL rate
        + durability lag."""
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            dash = json.load(f)
        panels = {p["title"]: p for p in dash["panels"]}
        retry = panels["Sink write retries and dead-letter rate"]
        exprs = " ".join(t["expr"] for t in retry["targets"])
        assert "sink_write_retries_total" in exprs
        assert "sink_write_failures_total" in exprs
        assert "sink_deadletter_total" in exprs
        depth = panels["Dead-letter depth and transport retries"]
        exprs = " ".join(t["expr"] for t in depth["targets"])
        assert "sink_deadletter_depth" in exprs
        assert "mesh_member_retries_total" in exprs
        assert "faults_injected_total" in exprs
        wal = panels["Mesh coordinator journal (WAL rate, durability "
                     "lag)"]
        exprs = " ".join(t["expr"] for t in wal["targets"])
        assert "mesh_journal_records_total" in exprs
        assert "mesh_journal_lag_seconds" in exprs
        assert "mesh_journal_unsynced_records" in exprs

    def test_traffic_dashboards_have_four_topn_tables(self):
        # reference viz.json serves four top-N tables: src/dst IPs AND
        # src/dst ports — both dashboard variants must carry all four
        for sub in ("dashboards", "dashboards-ch"):
            with open(os.path.join(DEPLOY, "grafana", sub,
                                   "traffic.json")) as f:
                dash = json.load(f)
            titles = {p["title"] for p in dash["panels"]}
            for want in ("Top source IPs", "Top destination IPs",
                         "Top source ports", "Top destination ports"):
                assert want in titles, (sub, want)

    def test_datasource_provisioning(self):
        pg = load("grafana/datasources.yml")
        ch = load("grafana/datasources-ch.yml")
        assert {d["name"] for d in pg["datasources"]} == {"Prometheus",
                                                          "PostgreSQL"}
        assert any(d["type"].endswith("clickhouse-datasource")
                   for d in ch["datasources"])


class TestDashboardHonesty:
    """Every panel query must resolve against the actually-exported
    surface: Prometheus exprs against the metric names the real services
    register, SQL against the sink DDL. Guards against silent drift
    between dashboards and code (the class of gap that once hid the
    missing nf-delay summary)."""

    PROM_FUNCS = {"rate", "irate", "sum", "avg", "max", "min", "increase",
                  "by", "histogram_quantile", "time", "le",
                  # scrape-level label (vector-match key in alert exprs)
                  "instance",
                  # sketch-audit family label (by-clause key)
                  "family",
                  # flowhistory record-kind label (by-clause key)
                  "kind",
                  # binary-op/matching keywords (alert exprs)
                  "and", "or", "unless", "on", "ignoring"}
    SQL_KEYWORDS = {"select", "from", "where", "group", "by", "order",
                    "limit", "as", "between", "and", "or", "desc", "asc",
                    "in", "not", "time", "case", "when", "then", "else",
                    "end"}
    SQL_FUNCS = {"to_timestamp", "sum", "max", "min", "avg", "concat",
                 "toString", "multiIf"}
    GRAFANA_MACROS = {"__timeFrom", "__timeTo", "__timeFilter",
                      "__fromTime", "__toTime"}

    @staticmethod
    def all_panel_queries():
        import glob

        out = []
        for path in (glob.glob(os.path.join(DEPLOY, "grafana", "dashboards",
                                            "*.json"))
                     + glob.glob(os.path.join(DEPLOY, "grafana",
                                              "dashboards-ch", "*.json"))):
            with open(path) as f:
                dash = json.load(f)
            for panel in dash.get("panels", []):
                for target in panel.get("targets", []):
                    expr = target.get("expr")
                    sql = target.get("rawSql") or target.get("query")
                    out.append((os.path.basename(path), panel["title"],
                                expr, sql))
        return out

    @staticmethod
    def exported_metric_names():
        """Every series name the REAL services' /metrics would serve:
        registered family names PLUS the exposition-level series the
        renderers derive from them (histogram ``_bucket``/``_sum``/
        ``_count``, summary ``_sum``/``_count``) — so a dashboard expr
        over ``..._bucket`` is honest exactly when a scrape would
        resolve it."""
        import re

        from flow_pipeline_tpu.collector import (CollectorConfig,
                                                 CollectorServer)
        from flow_pipeline_tpu.engine.worker import StreamWorker
        from flow_pipeline_tpu.obs import REGISTRY, MetricsRegistry

        from flow_pipeline_tpu.engine import Supervisor

        from flow_pipeline_tpu.gateway import SnapshotGateway
        from flow_pipeline_tpu.history import register_history_metrics
        from flow_pipeline_tpu.mesh import MeshCoordinator, MeshMember
        from flow_pipeline_tpu.models.ddos import DDoSDetector
        from flow_pipeline_tpu.models.spread import SpreadModel
        from flow_pipeline_tpu.obs.audit import SpreadAudit
        from flow_pipeline_tpu.serve import SnapshotStore
        from flow_pipeline_tpu.sink import MemorySink, ResilientSink
        from flow_pipeline_tpu.utils import faults as _faults

        reg = MetricsRegistry()
        CollectorServer(None, CollectorConfig(netflow_addr=None,
                                              sflow_addr=None), registry=reg)
        StreamWorker(consumer=None, models={})  # registers on the global
        Supervisor(lambda: None)  # worker_restarts_total
        MeshCoordinator([], 2)  # mesh_* families (incl. journal_*)
        MeshMember("honesty", None, None, None)  # mesh_member_retries
        SnapshotStore()  # serve_* families (eager registration)
        SnapshotGateway([SnapshotStore()])  # gateway_* families
        ResilientSink(MemorySink())  # sink retry/dead-letter families
        DDoSDetector()  # flow_entropy gauges (eager registration)
        SpreadModel()  # spread_top_max (eager registration)
        SpreadAudit({})  # sketch_spread_* audit families
        register_history_metrics()  # history_* archive families
        assert _faults.FAULTS.m_injected is not None  # faults_injected
        names = set(reg._metrics) | set(REGISTRY._metrics)
        for text in (reg.render(), REGISTRY.render()):
            for line in text.splitlines():
                m = re.match(r"([a-zA-Z_:][a-zA-Z0-9_:]*)[{ ]", line)
                if m and not line.startswith("#"):
                    names.add(m.group(1))
        return names

    def test_prometheus_exprs_use_registered_metrics(self):
        import re

        names = self.exported_metric_names()
        checked = 0
        for dash, title, expr, _ in self.all_panel_queries():
            if not expr:
                continue
            bare = re.sub(r"\{[^}]*\}", "", expr)
            bare = re.sub(r"\[[^\]]*\]", "", bare)
            idents = set(re.findall(r"[a-zA-Z_][a-zA-Z0-9_]*", bare))
            metrics = idents - self.PROM_FUNCS
            assert metrics, f"{dash}/{title}: no metric found in {expr!r}"
            for m in metrics:
                assert m in names, (
                    f"{dash}/{title}: {m!r} is not a registered metric"
                )
                checked += 1
        assert checked >= 15  # the surface is real, not vacuously empty

    def test_alert_exprs_use_registered_metrics(self):
        """deploy/prometheus/alerts.yml under the same honesty contract
        as the dashboards: every metric identifier in every alert expr
        must resolve against the rendered exposition surface — an alert
        on a typo'd series never fires, which is worse than no alert."""
        import re

        doc = load("prometheus/alerts.yml")
        names = self.exported_metric_names()
        rules = [r for g in doc["groups"] for r in g["rules"]]
        assert len(rules) >= 6  # the r15 satellite's floor
        checked = 0
        for rule in rules:
            expr = rule["expr"]
            bare = re.sub(r"\{[^}]*\}", "", expr)
            bare = re.sub(r"\[[^\]]*\]", "", bare)
            bare = re.sub(r'"[^"]*"', "", bare)
            idents = set(re.findall(r"[a-zA-Z_][a-zA-Z0-9_]*", bare))
            metrics = idents - self.PROM_FUNCS
            assert metrics, f"{rule['alert']}: no metric in {expr!r}"
            for m in metrics:
                assert m in names, (
                    f"alert {rule['alert']}: {m!r} is not a registered "
                    "metric")
                checked += 1
            assert rule.get("labels", {}).get("severity"), rule["alert"]
            assert "summary" in rule.get("annotations", {}), rule["alert"]
        assert checked >= 8
        # the audit error-ratio p99 rule the r15 satellite names
        assert any("sketch_estimate_error_ratio_bucket" in r["expr"]
                   for r in rules)
        # the flowchaos rules the r17 satellite names: dead-letter
        # backlog (> 0 pages), sink retry rate, coordinator journal lag
        assert any("sink_deadletter_depth" in r["expr"] for r in rules)
        assert any("sink_write_retries_total" in r["expr"]
                   for r in rules)
        assert any("mesh_journal_lag_seconds" in r["expr"]
                   for r in rules)
        # the flowguard rule the r20 satellite names: shedding by
        # policy pages — sampled answers / bounced readers mean
        # capacity is short even though nothing crashed
        assert any("guard_shed_total" in r["expr"] for r in rules)
        # the flowspread rules the r21 satellite names: the two
        # detector pagers on the per-model max-distinct gauge, and the
        # entropy-collapse companion gated on a warm baseline
        by_name = {r["alert"]: r for r in rules}
        assert 'model="superspreaders"' in \
            by_name["SuperspreaderDetected"]["expr"]
        assert 'model="portscan"' in by_name["PortScanDetected"]["expr"]
        ent = by_name["EntropyCollapse"]["expr"]
        assert "flow_entropy" in ent and "flow_entropy_baseline" in ent
        # the flowhistory rules the r22 satellite names: a damaged
        # archive segment pages (those versions are gone forever), and
        # so does the archive lagging the live feed
        assert "history_damage_skipped_total" in \
            by_name["HistoryArchiveDamaged"]["expr"]
        assert "history_lag_versions" in \
            by_name["HistoryArchiveLagging"]["expr"]

    def test_alerts_wired_into_prometheus_and_compose(self):
        """The rules file must actually be evaluated: prometheus.yml
        names it under rule_files, and every compose topology mounts it
        next to the scrape config."""
        prom = load("prometheus/prometheus.yml")
        assert "alerts.yml" in prom.get("rule_files", [])
        for path in COMPOSE_FILES:
            doc = load(path)
            vols = "\n".join(doc["services"]["prometheus"]["volumes"])
            assert "alerts.yml:/etc/prometheus/alerts.yml" in vols, path

    def test_sql_queries_resolve_against_ddl(self):
        import re

        from flow_pipeline_tpu.sink import ddl

        table_cols = dict(ddl.TABLE_COLUMNS)
        table_cols["flows"] = table_cols["flows"] + ["id", "date_inserted"]
        # ClickHouse dashboards query the CH tables' CamelCase columns;
        # extract the real column names straight from the CREATE statements
        for stmt in (ddl.CLICKHOUSE_FLOWS_RAW, ddl.CLICKHOUSE_FLOWS_5M,
                     ddl.CLICKHOUSE_TOP_TALKERS, ddl.CLICKHOUSE_TOP_SRC_PORTS,
                     ddl.CLICKHOUSE_TOP_DST_PORTS, ddl.CLICKHOUSE_DDOS_ALERTS):
            table = re.search(r"EXISTS (\w+)", stmt).group(1).lower()
            cols = [m.group(1) for m in
                    re.finditer(r"^\s+(\w+)\s+\w+", stmt, re.M)]
            table_cols[table] = sorted(set(table_cols.get(table, [])) | set(cols))
        checked = 0
        for dash, title, _, sql in self.all_panel_queries():
            if not sql:
                continue
            tables = [t.lower() for t in
                      re.findall(r"\bFROM\s+(\w+)", sql, re.I)]
            assert tables, f"{dash}/{title}: no FROM table in {sql!r}"
            allowed = set()
            for t in tables:
                assert t in table_cols, (
                    f"{dash}/{title}: table {t!r} has no DDL/TABLE_COLUMNS"
                )
                allowed.update(c.lower() for c in table_cols[t])
            aliases = {a.lower()
                       for a in re.findall(r"\bAS\s+(\w+)", sql, re.I)}
            bare = re.sub(r"'[^']*'", "", sql)  # drop string literals
            idents = {i.lower() for i in
                      re.findall(r"[a-zA-Z_][a-zA-Z0-9_]*", bare)}
            unknown = (idents - self.SQL_KEYWORDS
                       - {f.lower() for f in self.SQL_FUNCS}
                       - {m.lower() for m in self.GRAFANA_MACROS}
                       - aliases - set(tables) - allowed)
            assert not unknown, (
                f"{dash}/{title}: identifiers {sorted(unknown)} resolve to "
                f"no column of {tables} and no alias"
            )
            checked += len(allowed & idents)
        assert checked >= 20
