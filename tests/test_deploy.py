"""Deploy artifact sanity: YAML/JSON validity, topology shape parity with
the reference (topic `flows`, 2 partitions, restart policies, Grafana
provisioning paths), and dashboard queries referencing real tables."""

import json
import os

import pytest

yaml = pytest.importorskip("yaml")

DEPLOY = os.path.join(os.path.dirname(__file__), "..", "deploy")

COMPOSE_FILES = [
    "compose/postgres-mock.yml",
    "compose/postgres-collect.yml",
    "compose/clickhouse-mock.yml",
    "compose/clickhouse-collect.yml",
]


def load(path):
    with open(os.path.join(DEPLOY, path)) as f:
        return yaml.safe_load(f)


class TestCompose:
    @pytest.mark.parametrize("path", COMPOSE_FILES)
    def test_valid_yaml_with_processor(self, path):
        doc = load(path)
        assert "processor" in doc["services"]
        cmd = doc["services"]["processor"]["command"]
        assert "flowtpu-processor" in cmd
        assert "-metrics.addr" in cmd

    @pytest.mark.parametrize("path", COMPOSE_FILES)
    def test_topic_two_partitions(self, path):
        # reference default: topic flows, 2 partitions, RF 1
        doc = load(path)
        init = doc["services"]["kafka-init"]["command"]
        assert "--topic flows" in init
        assert "--partitions 2" in init
        assert "--replication-factor 1" in init

    @pytest.mark.parametrize("path", COMPOSE_FILES)
    def test_long_running_services_restart(self, path):
        doc = load(path)
        for name, svc in doc["services"].items():
            if name == "kafka-init":
                continue
            assert svc.get("restart") == "always", name

    def test_collect_topologies_expose_flow_ports(self):
        for path in ("compose/postgres-collect.yml",
                     "compose/clickhouse-collect.yml"):
            doc = load(path)
            ports = doc["services"]["goflow"]["ports"]
            assert any("6343" in p for p in ports)  # sFlow
            assert any("2055" in p for p in ports)  # NetFlow/IPFIX

    def test_fixedlen_on_clickhouse_paths(self):
        for path in ("compose/clickhouse-mock.yml",
                     "compose/clickhouse-collect.yml"):
            doc = load(path)
            producers = [
                s for n, s in doc["services"].items()
                if n in ("mocker", "goflow")
            ]
            assert any("fixedlen" in p["command"] for p in producers)

    def test_clickhouse_grafana_has_plugin_and_ch_dashboards(self):
        for path in ("compose/clickhouse-mock.yml",
                     "compose/clickhouse-collect.yml"):
            doc = load(path)
            graf = doc["services"]["grafana"]
            assert graf["environment"]["GF_INSTALL_PLUGINS"] == (
                "grafana-clickhouse-datasource"
            )
            vols = "\n".join(graf["volumes"])
            assert "dashboards-ch/traffic.json" in vols
            assert "dashboards/pipeline.json" in vols
            # every topology has prometheus for the pipeline dashboard
            assert "prometheus" in doc["services"]

    def test_postgres_processor_gets_password_env(self):
        for path in ("compose/postgres-mock.yml",
                     "compose/postgres-collect.yml"):
            doc = load(path)
            proc = doc["services"]["processor"]
            assert "POSTGRES_PASSWORD" in proc["environment"]

    def test_ch_dashboard_parses_and_uses_ch_datasource(self):
        with open(os.path.join(DEPLOY, "grafana", "dashboards-ch",
                               "traffic.json")) as f:
            dash = json.load(f)
        assert all(p["datasource"] == "ClickHouse" for p in dash["panels"])


class TestPrometheus:
    def test_scrapes_processor(self):
        doc = load("prometheus/prometheus.yml")
        targets = [
            t
            for job in doc["scrape_configs"]
            for sc in job["static_configs"]
            for t in sc["targets"]
        ]
        assert "processor:8081" in targets  # the reference never scraped :8081


class TestGrafana:
    def test_collector_dashboard_uses_collector_metrics(self):
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "collector.json")) as f:
            text = f.read()
        # the GoFlow-shaped surface (SURVEY §2-C12) our collector exports
        for metric in ("udp_traffic_bytes", "flow_traffic_bytes",
                       "flow_process_nf_flowset_records_sum",
                       "flow_process_sf_samples_sum",
                       "flow_process_nf_errors_count",
                       "flow_process_nf_templates_count",
                       "flow_summary_decoding_time_us", "flow_decoder_count"):
            assert metric in text

    def test_dashboards_parse_and_reference_real_tables(self):
        for name in ("traffic.json", "pipeline.json", "collector.json"):
            with open(os.path.join(DEPLOY, "grafana", "dashboards", name)) as f:
                dash = json.load(f)
            assert dash["panels"]
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "traffic.json")) as f:
            text = f.read()
        from flow_pipeline_tpu.sink.ddl import SQLITE_TABLES

        for table in ("flows_5m", "top_talkers", "ddos_alerts"):
            assert table in text
            assert table in SQLITE_TABLES

    def test_pipeline_dashboard_uses_exported_metrics(self):
        with open(os.path.join(DEPLOY, "grafana", "dashboards",
                               "pipeline.json")) as f:
            text = f.read()
        for metric in ("flows_processed_total", "insert_count",
                       "consumer_lag", "flow_processing_time_us"):
            assert metric in text

    def test_datasource_provisioning(self):
        pg = load("grafana/datasources.yml")
        ch = load("grafana/datasources-ch.yml")
        assert {d["name"] for d in pg["datasources"]} == {"Prometheus",
                                                          "PostgreSQL"}
        assert any(d["type"].endswith("clickhouse-datasource")
                   for d in ch["datasources"])
