"""sort_groupby device op vs the numpy oracle, including padding/invalid rows
and jit cache friendliness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flow_pipeline_tpu.ops.segment import sort_groupby


def np_groupby(keys, values, valid):
    agg = {}
    for i in range(len(keys)):
        if not valid[i]:
            continue
        k = tuple(int(x) for x in keys[i])
        s, c = agg.get(k, (np.zeros(values.shape[1], np.int64), 0))
        agg[k] = (s + values[i], c + 1)
    return agg


class TestSortGroupby:
    @pytest.mark.parametrize("n,w,vdim,card", [(64, 2, 1, 5), (256, 3, 2, 40), (512, 6, 2, 300)])
    def test_matches_numpy(self, rng, n, w, vdim, card):
        keys = rng.integers(0, card, size=(n, w)).astype(np.uint32)
        values = rng.integers(0, 1000, size=(n, vdim)).astype(np.int32)
        valid = rng.random(n) > 0.1
        uk, sums, counts, ng = jax.jit(sort_groupby)(
            jnp.asarray(keys), jnp.asarray(values), jnp.asarray(valid)
        )
        expect = np_groupby(keys, values, valid)
        ng = int(ng)
        assert ng == len(expect)
        for i in range(ng):
            k = tuple(int(x) for x in np.asarray(uk[i]))
            s, c = expect[k]
            np.testing.assert_array_equal(np.asarray(sums[i]), s)
            assert int(counts[i]) == c

    def test_all_invalid(self):
        uk, sums, counts, ng = sort_groupby(
            jnp.zeros((16, 2), jnp.uint32),
            jnp.ones((16, 1), jnp.int32),
            jnp.zeros(16, bool),
        )
        assert int(ng) == 0
        assert int(jnp.sum(sums)) == 0

    def test_single_group(self):
        n = 32
        uk, sums, counts, ng = sort_groupby(
            jnp.ones((n, 3), jnp.uint32) * 7,
            jnp.ones((n, 2), jnp.int32),
            jnp.ones(n, bool),
        )
        assert int(ng) == 1
        assert sums[0].tolist() == [n, n]
        assert int(counts[0]) == n

    def test_groups_lead_output(self, rng):
        keys = rng.integers(0, 4, size=(128, 1)).astype(np.uint32)
        valid = rng.random(128) > 0.5
        uk, sums, counts, ng = sort_groupby(
            jnp.asarray(keys), jnp.ones((128, 1), jnp.int32), jnp.asarray(valid)
        )
        ng = int(ng)
        assert (np.asarray(counts[:ng]) > 0).all()
        # rows at/after n_groups are padding or the sentinel group
        assert (np.asarray(uk[ng + 1 :]) == 0xFFFFFFFF).all() or ng >= 127
