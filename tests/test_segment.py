"""sort_groupby device op vs the numpy oracle, including padding/invalid rows
and jit cache friendliness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flow_pipeline_tpu.ops.segment import sort_groupby


def np_groupby(keys, values, valid):
    agg = {}
    for i in range(len(keys)):
        if not valid[i]:
            continue
        k = tuple(int(x) for x in keys[i])
        s, c = agg.get(k, (np.zeros(values.shape[1], np.int64), 0))
        agg[k] = (s + values[i], c + 1)
    return agg


class TestSortGroupby:
    @pytest.mark.parametrize("n,w,vdim,card", [(64, 2, 1, 5), (256, 3, 2, 40), (512, 6, 2, 300)])
    def test_matches_numpy(self, rng, n, w, vdim, card):
        keys = rng.integers(0, card, size=(n, w)).astype(np.uint32)
        values = rng.integers(0, 1000, size=(n, vdim)).astype(np.int32)
        valid = rng.random(n) > 0.1
        uk, sums, counts, ng = jax.jit(sort_groupby)(
            jnp.asarray(keys), jnp.asarray(values), jnp.asarray(valid)
        )
        expect = np_groupby(keys, values, valid)
        ng = int(ng)
        assert ng == len(expect)
        for i in range(ng):
            k = tuple(int(x) for x in np.asarray(uk[i]))
            s, c = expect[k]
            np.testing.assert_array_equal(np.asarray(sums[i]), s)
            assert int(counts[i]) == c

    def test_all_invalid(self):
        uk, sums, counts, ng = sort_groupby(
            jnp.zeros((16, 2), jnp.uint32),
            jnp.ones((16, 1), jnp.int32),
            jnp.zeros(16, bool),
        )
        assert int(ng) == 0
        assert int(jnp.sum(sums)) == 0

    def test_single_group(self):
        n = 32
        uk, sums, counts, ng = sort_groupby(
            jnp.ones((n, 3), jnp.uint32) * 7,
            jnp.ones((n, 2), jnp.int32),
            jnp.ones(n, bool),
        )
        assert int(ng) == 1
        assert sums[0].tolist() == [n, n]
        assert int(counts[0]) == n

    def test_valid_all_ones_key_counted(self):
        # a VALID row whose whole key tuple is the 0xFFFFFFFF sentinel
        # (e.g. the ff..ff address in a raw address-keyed layout) shares a
        # segment with padding rows but must still be counted exactly
        n = 16
        keys = np.zeros((n, 2), np.uint32)
        keys[3] = 0xFFFFFFFF  # valid all-ones key
        keys[7] = 0xFFFFFFFF
        values = np.arange(n, dtype=np.int32)[:, None] + 1
        valid = np.ones(n, bool)
        valid[8:] = False  # padding also lands on the sentinel key
        uk, sums, counts, ng = sort_groupby(
            jnp.asarray(keys), jnp.asarray(values), jnp.asarray(valid)
        )
        ng = int(ng)
        assert ng == 2  # the zero group and the all-ones group
        rows = {
            tuple(np.asarray(uk[i])): (int(sums[i, 0]), int(counts[i]))
            for i in range(ng)
        }
        assert rows[(0, 0)] == (1 + 2 + 3 + 5 + 6 + 7, 6)
        assert rows[(0xFFFFFFFF, 0xFFFFFFFF)] == (4 + 8, 2)

    def test_valid_all_ones_key_counted_float(self):
        from flow_pipeline_tpu.ops.segment import sort_groupby_float

        keys = np.zeros((8, 1), np.uint32)
        keys[2] = 0xFFFFFFFF
        values = np.ones((8, 1), np.float32) * 2.5
        valid = np.array([1, 1, 1, 1, 0, 0, 0, 0], bool)
        uk, sums, counts = sort_groupby_float(
            jnp.asarray(keys), jnp.asarray(values), jnp.asarray(valid)
        )
        rows = {
            int(np.asarray(uk[i, 0])): (float(sums[i, 0]), int(counts[i]))
            for i in range(8)
            if int(counts[i]) > 0
        }
        assert rows[0] == (7.5, 3)
        assert rows[0xFFFFFFFF] == (2.5, 1)

    def test_groups_lead_output(self, rng):
        keys = rng.integers(0, 4, size=(128, 1)).astype(np.uint32)
        valid = rng.random(128) > 0.5
        uk, sums, counts, ng = sort_groupby(
            jnp.asarray(keys), jnp.ones((128, 1), jnp.int32), jnp.asarray(valid)
        )
        ng = int(ng)
        assert (np.asarray(counts[:ng]) > 0).all()
        # rows at/after n_groups are padding or the sentinel group
        assert (np.asarray(uk[ng + 1 :]) == 0xFFFFFFFF).all() or ng >= 127


class TestHashGroupby:
    """hash_groupby(_float) must agree with the numpy oracle / the
    lexicographic path everywhere sort_groupby does — group ORDER is the
    only licensed difference (hash order vs lex order)."""

    @pytest.mark.parametrize(
        "n,w,vdim,card",
        [(64, 2, 1, 5), (256, 3, 2, 40), (512, 6, 2, 300), (512, 11, 2, 500)],
    )
    def test_matches_numpy(self, rng, n, w, vdim, card):
        from flow_pipeline_tpu.ops.segment import hash_groupby

        keys = rng.integers(0, card, size=(n, w)).astype(np.uint32)
        values = rng.integers(0, 1000, size=(n, vdim)).astype(np.int32)
        valid = rng.random(n) > 0.1
        uk, sums, counts, ng, collided = jax.jit(hash_groupby)(
            jnp.asarray(keys), jnp.asarray(values), jnp.asarray(valid)
        )
        assert not bool(collided)
        expect = np_groupby(keys, values, valid)
        ng = int(ng)
        assert ng == len(expect)
        for i in range(ng):
            k = tuple(int(x) for x in np.asarray(uk[i]))
            s, c = expect[k]
            np.testing.assert_array_equal(np.asarray(sums[i]), s)
            assert int(counts[i]) == c

    def test_float_matches_sort_path(self, rng):
        from flow_pipeline_tpu.ops.segment import (
            hash_groupby_float,
            sort_groupby_float,
        )

        n = 256
        keys = rng.integers(0, 37, size=(n, 4)).astype(np.uint32)
        values = rng.integers(0, 1500, size=(n, 2)).astype(np.float32)
        valid = rng.random(n) > 0.2
        hu, hs, hc = hash_groupby_float(
            jnp.asarray(keys), jnp.asarray(values), jnp.asarray(valid))
        su, ss, sc = sort_groupby_float(
            jnp.asarray(keys), jnp.asarray(values), jnp.asarray(valid))

        def rows(u, s, c):
            return {
                tuple(int(x) for x in np.asarray(u[i])): (
                    np.asarray(s[i]).tolist(), int(c[i]))
                for i in range(n) if int(c[i]) > 0
            }

        assert rows(hu, hs, hc) == rows(su, ss, sc)

    def test_real_groups_lead_output(self, rng):
        from flow_pipeline_tpu.ops.segment import hash_groupby

        keys = rng.integers(0, 6, size=(128, 2)).astype(np.uint32)
        valid = rng.random(128) > 0.5
        uk, sums, counts, ng, _ = hash_groupby(
            jnp.asarray(keys), jnp.ones((128, 1), jnp.int32),
            jnp.asarray(valid))
        ng = int(ng)
        # device slicing [:n_groups] must capture every real group
        assert (np.asarray(counts[:ng]) > 0).all()
        assert (np.asarray(counts[ng:]) == 0).all()

    def test_all_invalid(self):
        from flow_pipeline_tpu.ops.segment import hash_groupby

        uk, sums, counts, ng, collided = hash_groupby(
            jnp.zeros((16, 2), jnp.uint32),
            jnp.ones((16, 1), jnp.int32),
            jnp.zeros(16, bool),
        )
        assert int(ng) == 0 and not bool(collided)
        assert int(jnp.sum(sums)) == 0

    def test_valid_all_ones_key_gets_own_group(self):
        # Unlike sort_groupby (where a valid all-1s KEY shares the padding
        # segment), the hash path groups by hash(key) != sentinel hash, so
        # the all-1s key stands alone with exact sums — strictly cleaner.
        from flow_pipeline_tpu.ops.segment import hash_groupby

        keys = np.zeros((8, 2), np.uint32)
        keys[1] = 0xFFFFFFFF
        valid = np.array([1, 1, 1, 0, 0, 0, 0, 0], bool)
        uk, sums, counts, ng, collided = hash_groupby(
            jnp.asarray(keys), jnp.ones((8, 1), jnp.int32),
            jnp.asarray(valid))
        assert not bool(collided)
        rows = {
            tuple(np.asarray(uk[i]).tolist()): (int(sums[i, 0]), int(counts[i]))
            for i in range(int(ng))
        }
        assert rows[(0, 0)] == (2, 2)
        assert rows[(0xFFFFFFFF, 0xFFFFFFFF)] == (1, 1)

    def test_collision_detected(self):
        # Force a 64-bit collision through the internal grouped kernel:
        # two DIFFERENT key tuples arriving with identical sorted hashes
        # must raise the collided flag (the public wrappers make this a
        # ~2^-64 event; exactness callers re-run the lexicographic path).
        from flow_pipeline_tpu.ops.segment import _hash_grouped

        n = 8
        sh = np.zeros((n, 2), np.uint32)  # everyone "hashes" equal
        sk = np.zeros((n, 2), np.uint32)
        sk[3] = (1, 2)  # ...but keys differ
        uniq, sums, counts, collided = _hash_grouped(
            jnp.asarray(sh), jnp.asarray(sk),
            jnp.ones((n, 1), jnp.int32), jnp.ones(n, jnp.int32), True)
        assert bool(collided)

    def test_no_false_collision_on_padding(self):
        from flow_pipeline_tpu.ops.segment import hash_groupby_float

        keys = np.arange(32, dtype=np.uint32).reshape(16, 2)
        valid = np.zeros(16, bool)
        valid[:4] = True
        uniq, sums, counts, collided = hash_groupby_float(
            jnp.asarray(keys), jnp.ones((16, 1), jnp.float32),
            jnp.asarray(valid), detect=True)
        assert not bool(collided)
