"""BENCH_*.json artifacts are valid JSON documents (r19 satellite).

Multi-record bench modes (cms, sweep, fused...) used to leave
redirected artifacts as JSON-lines that ``json.load`` rejects — every
loader script had to know the quirk. bench.py's dispatcher now tees the
mode functions' streaming lines to stderr and renders ONE valid JSON
document on stdout; ``load_bench`` reads both the new shapes and the
pre-r19 JSON-lines layout. The repo gate: every CHECKED-IN artifact
must ``json.load``.
"""

from __future__ import annotations

import glob
import io
import json
import os

import bench

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestCheckedInArtifacts:
    def test_every_bench_artifact_is_valid_json(self):
        paths = sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))
        assert paths, "no BENCH_*.json artifacts found"
        for path in paths:
            with open(path) as f:
                text = f.read()
            if not text.strip():
                continue  # r12: a placeholder the round left empty
            json.loads(text)  # raises -> the artifact regressed

    def test_load_bench_reads_every_artifact(self):
        for path in sorted(glob.glob(os.path.join(REPO, "BENCH_*.json"))):
            records = bench.load_bench(path)
            assert all(isinstance(r, (dict, list)) for r in records)


class TestLoaderAndRenderer:
    def test_load_bench_accepts_all_three_shapes(self, tmp_path):
        rec = {"metric": "x", "value": 1}
        one = tmp_path / "one.json"
        one.write_text(json.dumps(rec))
        assert bench.load_bench(str(one)) == [rec]
        arr = tmp_path / "arr.json"
        arr.write_text(json.dumps([rec, rec]))
        assert bench.load_bench(str(arr)) == [rec, rec]
        jsonl = tmp_path / "old.json"  # the pre-r19 layout
        jsonl.write_text(json.dumps(rec) + "\n" + json.dumps(rec) + "\n")
        assert bench.load_bench(str(jsonl)) == [rec, rec]
        empty = tmp_path / "empty.json"
        empty.write_text("\n")
        assert bench.load_bench(str(empty)) == []

    def test_render_document_round_trips(self):
        one = [{"a": 1}]
        assert json.loads(bench._render_document(one)) == one[0]
        many = [{"a": 1}, {"b": [2, 3]}, {"c": "x"}]
        assert json.loads(bench._render_document(many)) == many

    def test_tee_streams_lines_and_parses(self):
        progress = io.StringIO()
        tee = bench._JsonLineTee(progress)
        tee.write(json.dumps({"a": 1}) + "\n")
        tee.write('{"b": ')  # a record split across writes
        tee.write('2}\n')
        tee.write('{"partial": true}')  # no trailing newline
        records = tee.finish()
        assert records == [{"a": 1}, {"b": 2}, {"partial": True}]
        # every completed line reached the progress stream
        assert progress.getvalue().count("\n") == 3

    def test_tee_drops_non_json_noise_loudly(self):
        progress = io.StringIO()
        tee = bench._JsonLineTee(progress)
        tee.write("not json\n")
        tee.write(json.dumps({"ok": 1}) + "\n")
        assert tee.finish() == [{"ok": 1}]
        assert "non-JSON" in progress.getvalue()
