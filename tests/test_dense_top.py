"""Dense exact top-K (models.dense_top): exact vs the oracle, windowed
lifecycle compatibility, sharded equivalence, and checkpoint round-trip."""

import numpy as np
import pytest

from flow_pipeline_tpu.engine import WindowedHeavyHitter
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.models import DenseTopConfig, DenseTopKModel
from flow_pipeline_tpu.models.oracle import topk_exact
from flow_pipeline_tpu.schema.batch import FlowBatch


def traffic(n=6000, seed=21):
    return FlowGenerator(ZipfProfile(n_keys=200, alpha=1.3), seed=seed,
                         t0=1_699_999_800, rate=50.0).batch(n)


class TestDenseTopK:
    def test_zero_byte_flows_stay_valid(self):
        """A port seen ONLY via zero-byte flows (count > 0) must appear as
        a valid row: validity derives from the count plane, not from the
        bytes-based ranking value."""
        b = FlowBatch.empty(4)
        b.columns["src_port"][:] = [7, 7, 9, 9]
        b.columns["bytes"][:] = [0, 0, 500, 100]
        b.columns["packets"][:] = [1, 1, 1, 1]
        model = DenseTopKModel(DenseTopConfig(batch_size=4))
        model.update(b)
        top = model.top(3)
        rows = {int(p): (int(c), bool(v)) for p, c, v in
                zip(top["src_port"], top["count"], top["valid"])}
        assert rows[9] == (2, True)
        assert rows[7] == (2, True)  # zero bytes, but two real flows

    def test_exact_vs_oracle(self):
        batch = traffic()
        m = DenseTopKModel(DenseTopConfig(key_col="src_port",
                                          batch_size=1024))
        m.update(batch)
        # fetch a buffer past k so rank-boundary TIES (equal byte totals
        # broken differently) cannot hide an exact-match failure
        top = m.top(40)
        got = {int(p): (int(b), int(c))
               for p, b, c in zip(top["src_port"], top["bytes"],
                                  top["count"])}
        oracle = topk_exact(batch, ["src_port"], 10)
        assert len(oracle["src_port"]) == 10  # enough distinct ports
        for i in range(10):
            port = int(np.atleast_1d(oracle["src_port"][i])[0])
            # EXACT: identical values, not a <=1% gate
            assert got[port] == (int(oracle["bytes"][i]),
                                 int(oracle["count"][i]))

    def test_accumulates_and_resets(self):
        batch = traffic(2000)
        m = DenseTopKModel(DenseTopConfig(batch_size=512))
        m.update(batch)
        m.update(batch)
        top = m.top(1)
        oracle = topk_exact(batch, ["src_port"], 1)
        assert int(top["bytes"][0]) == 2 * int(oracle["bytes"][0])
        m.reset()
        assert not m.top(5)["valid"].any()

    def test_windowed_lifecycle(self):
        # DenseTopKModel drives under WindowedHeavyHitter unchanged
        g = FlowGenerator(ZipfProfile(n_keys=50, alpha=1.4), seed=5,
                          t0=1_699_999_800, rate=20.0)
        wm = WindowedHeavyHitter(
            DenseTopConfig(key_col="dst_port", batch_size=512),
            k=10, model_cls=DenseTopKModel,
        )
        for _ in range(3):
            wm.update(g.batch(2000))  # 300s -> crosses a window boundary
        rows = wm.flush(force=True)
        assert rows and all("dst_port" in r and "timeslot" in r
                            for r in [
                                {k: v[i] for k, v in row.items()}
                                for row in rows for i in range(1)
                            ])

    def test_sharded_matches_single_chip(self):
        from flow_pipeline_tpu.parallel import ShardedDenseTopK, make_mesh

        batch = traffic(4096)
        cfg = DenseTopConfig(key_col="src_port", batch_size=512)
        single = DenseTopKModel(cfg)
        single.update(batch)
        sharded = ShardedDenseTopK(cfg, make_mesh(4))
        sharded.update(batch)
        t1, t2 = single.top(15), sharded.top(15)
        for k in t1:
            np.testing.assert_array_equal(t1[k], t2[k])

    def test_checkpoint_roundtrip_via_worker(self, tmp_path):
        from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
        from flow_pipeline_tpu.sink import MemorySink
        from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer

        bus = InProcessBus()
        bus.create_topic("flows", 1)
        Producer(bus, fixedlen=True).send_many(traffic(1500).to_messages())

        def make(path):
            return StreamWorker(
                Consumer(bus, fixedlen=True),
                {"top_src_ports": WindowedHeavyHitter(
                    DenseTopConfig(batch_size=512), k=5,
                    model_cls=DenseTopKModel)},
                [MemorySink()],
                WorkerConfig(poll_max=512, snapshot_every=1,
                             checkpoint_path=path),
            )

        path = str(tmp_path / "ckpt")
        w1 = make(path)
        w1.run_once()
        totals_before = np.asarray(w1.models["top_src_ports"].model.totals)

        w2 = make(path)
        assert w2.restore()
        np.testing.assert_array_equal(
            np.asarray(w2.models["top_src_ports"].model.totals),
            totals_before,
        )

    def test_exact_past_float32_mantissa(self):
        # the 16-bit-plane + carry design must stay exact where float32
        # accumulation loses increments (> 2^24 per cell per window)
        cfg = DenseTopConfig(key_col="src_port", batch_size=1024)
        m = DenseTopKModel(cfg)
        n = 1024
        batch = traffic(n)
        batch.columns["src_port"][:] = 443  # one hot port
        batch.columns["bytes"][:] = 60_000
        rounds = 300  # 1024 * 60000 * 300 = 18.4e9 >> 2^24 (and > 2^32)
        for _ in range(rounds):
            m.update(batch)
        top = m.top(1)
        assert int(top["src_port"][0]) == 443
        assert int(top["bytes"][0]) == n * 60_000 * rounds  # EXACT
        assert int(top["count"][0]) == n * rounds

    def test_checkpoint_kind_mismatch_skipped(self, tmp_path, caplog):
        # a checkpoint whose port model was sketch-backed must not be
        # loaded into a dense-backed model (wrong state family): skip
        # loudly, never corrupt
        from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
        from flow_pipeline_tpu.models import HeavyHitterConfig
        from flow_pipeline_tpu.models.heavy_hitter import HeavyHitterModel
        from flow_pipeline_tpu.sink import MemorySink
        from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer

        bus = InProcessBus()
        bus.create_topic("flows", 1)
        Producer(bus, fixedlen=True).send_many(traffic(1000).to_messages())
        path = str(tmp_path / "ckpt")

        sketch_backed = StreamWorker(
            Consumer(bus, fixedlen=True, group="old"),
            {"top_src_ports": WindowedHeavyHitter(
                HeavyHitterConfig(key_cols=("src_port",), batch_size=512,
                                  width=1 << 10, capacity=32), k=5,
                model_cls=HeavyHitterModel)},
            [MemorySink()],
            WorkerConfig(poll_max=512, snapshot_every=1,
                         checkpoint_path=path),
        )
        sketch_backed.run_once()

        dense_backed = StreamWorker(
            Consumer(bus, fixedlen=True, group="new"),
            {"top_src_ports": WindowedHeavyHitter(
                DenseTopConfig(batch_size=512), k=5,
                model_cls=DenseTopKModel)},
            [MemorySink()],
            WorkerConfig(poll_max=512, checkpoint_path=path),
        )
        assert dense_backed.restore()
        inner = dense_backed.models["top_src_ports"].model
        assert not hasattr(inner, "state")  # no stray sketch attribute
        assert int(np.asarray(inner.totals).sum()) == 0  # untouched


class TestLargeBatchExactness:
    def test_batch_32768_and_subchunked_65536_exact(self):
        """The two-stage carry admits 2^15-row scatters and internal
        sub-chunking admits any caller batch; both must stay exact under
        the adversarial worst case (every row on one cell, saturated
        16-bit lo plane)."""
        import jax.numpy as jnp

        from flow_pipeline_tpu.models.dense_top import (
            _planes_to_uint64,
            dense_update,
        )

        for n in (32768, 65536):
            cfg = DenseTopConfig(key_col="src_port", batch_size=n,
                                 scale_col=None)
            totals = jnp.zeros((cfg.domain, 3, 2), jnp.int32)
            cols = {
                "src_port": jnp.full(n, 443, jnp.int32),
                "bytes": jnp.full(n, 0xFFFF, jnp.int32),  # saturated lo
                "packets": jnp.full(n, 1, jnp.int32),
            }
            valid = jnp.ones(n, bool)
            for _ in range(3):  # accumulate across batches too
                totals = dense_update(totals, cols, valid, config=cfg)
            vals = _planes_to_uint64(np.asarray(totals[443]))
            assert int(vals[0]) == 3 * n * 0xFFFF   # bytes
            assert int(vals[1]) == 3 * n            # packets
            assert int(vals[2]) == 3 * n            # count
