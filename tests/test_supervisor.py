"""engine/supervisor.py under repeated crash-restore cycles (flowchaos
satellite): backoff reset after a healthy era, factory/restore crashes
riding the same ladder as run crashes, and the checkpoint-restore
integration — a worker crash-looping through sink failures recovers to
EXACT output. (The basic restart/give-up tests live in
test_feed_supervisor.py, which is skipped without grpcio; this file
has no such gate — the supervisor itself needs none.)"""

import numpy as np
import pytest

from flow_pipeline_tpu.engine import (StreamWorker, Supervisor,
                                      SupervisorConfig, WorkerConfig)
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.models import WindowAggConfig, WindowAggregator
from flow_pipeline_tpu.sink import MemorySink
from flow_pipeline_tpu.transport import Consumer, InProcessBus


class _Clock:
    """Injectable monotonic clock: sleeps advance it, tests can jump it."""

    def __init__(self):
        self.now = 0.0
        self.sleeps = []

    def time(self):
        return self.now

    def sleep(self, s):
        self.sleeps.append(s)
        self.now += s


class TestBackoffLadder:
    def _crashing_supervisor(self, clock, crashes, **cfg):
        state = {"n": 0}

        class Worker:
            def run(self):
                state["n"] += 1
                if state["n"] <= crashes:
                    raise RuntimeError(f"crash {state['n']}")

            def finalize(self):
                pass

        sup = Supervisor(Worker,
                         SupervisorConfig(**cfg),
                         time_fn=clock.time, sleep_fn=clock.sleep)
        return sup

    def test_backoff_resets_after_healthy_era(self):
        """Crashes separated by more than window_seconds are unrelated
        incidents: the backoff must restart from backoff_initial, not
        keep compounding forever."""
        clock = _Clock()
        state = {"n": 0}

        class Worker:
            def run(self):
                state["n"] += 1
                if state["n"] in (1, 2):
                    raise RuntimeError("burst 1")
                if state["n"] == 3:
                    clock.now += 1000.0  # a long healthy run...
                    raise RuntimeError("fresh incident")  # ...then crash
                # state 4: clean exit

            def finalize(self):
                pass

        sup = Supervisor(Worker,
                         SupervisorConfig(max_restarts=5,
                                          window_seconds=300.0,
                                          backoff_initial=0.5,
                                          backoff_max=30.0),
                         time_fn=clock.time, sleep_fn=clock.sleep)
        sup.run()
        # burst 1: 0.5 then 1.0; the post-healthy-era crash resets to 0.5
        assert clock.sleeps == [0.5, 1.0, 0.5]
        assert sup.restarts == 3

    def test_crash_burst_gives_up(self):
        clock = _Clock()
        sup = self._crashing_supervisor(clock, crashes=99,
                                        max_restarts=2,
                                        window_seconds=300.0,
                                        backoff_initial=0.1,
                                        backoff_max=0.2)
        with pytest.raises(RuntimeError):
            sup.run()
        assert sup.restarts == 3  # 2 allowed restarts + the final crash
        assert clock.sleeps == [0.1, 0.2]  # capped at backoff_max

    def test_factory_crash_counts_as_restart(self):
        """A crash DURING restore/build (factory()) must ride the same
        backoff ladder — regression: it previously propagated straight
        out, so one corrupt-checkpoint read killed the supervisor that
        exists to absorb exactly that."""
        clock = _Clock()
        calls = []

        def factory():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("restore failed (corrupt checkpoint)")

            class Worker:
                def run(self):
                    pass

                def finalize(self):
                    pass

            return Worker()

        sup = Supervisor(factory,
                         SupervisorConfig(max_restarts=5,
                                          backoff_initial=0.1),
                         time_fn=clock.time, sleep_fn=clock.sleep)
        sup.run()
        assert len(calls) == 3
        assert sup.restarts == 2

    def test_factory_crash_loop_still_gives_up(self):
        clock = _Clock()

        def factory():
            raise RuntimeError("permanently corrupt")

        sup = Supervisor(factory,
                         SupervisorConfig(max_restarts=2,
                                          backoff_initial=0.01),
                         time_fn=clock.time, sleep_fn=clock.sleep)
        with pytest.raises(RuntimeError, match="permanently corrupt"):
            sup.run()
        assert sup.restarts == 3


# ---------------------------------------------------------------------------
# checkpoint-restore integration: crash cycles recover to exact output
# ---------------------------------------------------------------------------


N_FLOWS = 16_384
BATCH = 2048


def _bus():
    bus = InProcessBus()
    bus.create_topic("flows", 1)
    gen = FlowGenerator(ZipfProfile(n_keys=50, alpha=1.2), seed=4,
                        rate=60.0)  # multi-window: several flushes
    from flow_pipeline_tpu.schema import wire

    done = 0
    while done < N_FLOWS:
        n = min(8192, N_FLOWS - done)
        bus.produce_many("flows", wire.iter_raw_frames(
            gen.batch(n).to_wire()))
        done += n
    return bus


def _fold(tables):
    acc = {}
    for rec in tables.get("flows_5m", []):
        key = (rec["timeslot"], rec["src_as"], rec["dst_as"],
               rec["etype"])
        v = acc.setdefault(key, np.zeros(3, np.uint64))
        v += np.array([rec["bytes"], rec["packets"], rec["count"]],
                      np.uint64)
    return acc


class _SinkCrashingBefore:
    """Fails the first ``fails`` write ATTEMPTS before touching the
    inner sink — the flush dies, the step never commits, a restart
    replays the window from the checkpoint (at-least-once with no
    partial rows)."""

    def __init__(self, inner, fails):
        self.inner = inner
        self.fails = fails
        self.attempts = 0

    def write(self, table, rows):
        self.attempts += 1
        if self.attempts <= self.fails:
            raise ConnectionResetError(
                f"sink down (attempt {self.attempts})")
        self.inner.write(table, rows)


def _models():
    return {"flows_5m": WindowAggregator(
        WindowAggConfig(batch_size=BATCH))}


def test_repeated_crash_restore_cycles_stay_exact(tmp_path):
    """The worker-side recovery primitive, end to end: the sink kills
    the worker twice mid-stream (FlushError), the supervisor rebuilds
    through the checkpoint each time, and the folded flows_5m output
    equals a never-crashed run's exactly — replay re-emits only what
    was never committed."""
    # reference run: same stream, healthy sink
    clean = MemorySink()
    StreamWorker(Consumer(_bus(), "flows", fixedlen=True), _models(),
                 [clean],
                 WorkerConfig(poll_max=BATCH, snapshot_every=4)
                 ).run(stop_when_idle=True)

    sink = MemorySink()
    flaky = _SinkCrashingBefore(sink, fails=2)
    ckpt = str(tmp_path / "ckpt")
    bus = _bus()

    def factory():
        worker = StreamWorker(
            Consumer(bus, "flows", fixedlen=True), _models(), [flaky],
            WorkerConfig(poll_max=BATCH, snapshot_every=4,
                         checkpoint_path=ckpt))
        worker.restore()  # no-op on the first boot, the cycle after
        return worker

    sup = Supervisor(factory,
                     SupervisorConfig(max_restarts=5,
                                      backoff_initial=0.01,
                                      backoff_max=0.02),
                     stop_when_idle=True)
    sup.run()
    assert sup.restarts == 2  # both sink crashes absorbed
    f_clean, f_crashy = _fold(clean.tables), _fold(sink.tables)
    assert set(f_clean) == set(f_crashy)
    for k in f_clean:
        assert (f_crashy[k] == f_clean[k]).all(), k


def test_crash_during_restore_then_recovers(tmp_path):
    """Crash cycle where the RESTORE itself fails once (corrupt/locked
    checkpoint store): the supervisor must absorb it and the eventual
    run still drains the stream."""
    sink = MemorySink()
    bus = _bus()
    state = {"n": 0}

    def factory():
        state["n"] += 1
        if state["n"] == 1:
            raise OSError("checkpoint store unavailable")
        return StreamWorker(
            Consumer(bus, "flows", fixedlen=True), _models(), [sink],
            WorkerConfig(poll_max=BATCH, snapshot_every=4))

    sup = Supervisor(factory,
                     SupervisorConfig(max_restarts=3,
                                      backoff_initial=0.01),
                     stop_when_idle=True)
    sup.run()
    assert sup.restarts == 1
    assert sum(r["count"] for r in sink.tables["flows_5m"]) == N_FLOWS