"""Oracle tests: the numpy groupby against a brute-force dict reference
(two independent implementations must agree), plus flows_5m shape/semantics."""

import numpy as np

from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile, ZipfProfile
from flow_pipeline_tpu.models.oracle import exact_groupby, flows_5m, topk_exact


def brute_force_5m(batch):
    agg = {}
    c = batch.columns
    for i in range(len(batch)):
        slot = int(c["time_received"][i]) // 300 * 300
        key = (slot, int(c["src_as"][i]), int(c["dst_as"][i]), int(c["etype"][i]))
        b, p, n = agg.get(key, (0, 0, 0))
        agg[key] = (b + int(c["bytes"][i]), p + int(c["packets"][i]), n + 1)
    return agg


class TestExactGroupby:
    def test_matches_brute_force(self):
        g = FlowGenerator(MockerProfile(), seed=11, rate=10.0)  # spans windows
        batch = g.batch(3000)
        expect = brute_force_5m(batch)
        got = flows_5m(batch)
        assert len(got["timeslot"]) == len(expect)
        for i in range(len(got["timeslot"])):
            key = (
                int(got["timeslot"][i]),
                int(got["src_as"][i]),
                int(got["dst_as"][i]),
                int(got["etype"][i]),
            )
            b, p, n = expect[key]
            assert int(got["bytes"][i]) == b
            assert int(got["packets"][i]) == p
            assert int(got["count"][i]) == n

    def test_date_column(self):
        g = FlowGenerator(MockerProfile(), seed=1)
        got = flows_5m(g.batch(100))
        assert (got["date"] == got["timeslot"] // 86400).all()

    def test_addr_keys(self):
        g = FlowGenerator(ZipfProfile(n_keys=20), seed=3)
        batch = g.batch(1000)
        got = exact_groupby(batch, ["src_addr", "dst_addr"], timeslot=False)
        assert got["src_addr"].shape[1] == 4
        assert got["count"].sum() == 1000
        assert got["bytes"].sum() == batch.columns["bytes"].sum()

    def test_total_conservation(self):
        g = FlowGenerator(MockerProfile(), seed=4)
        batch = g.batch(5000)
        got = flows_5m(batch)
        assert got["bytes"].sum() == batch.columns["bytes"].astype(np.uint64).sum()
        assert got["count"].sum() == 5000


class TestTopK:
    def test_topk_is_sorted_and_correct(self):
        g = FlowGenerator(ZipfProfile(n_keys=500, alpha=1.5), seed=9)
        batch = g.batch(20000)
        full = exact_groupby(batch, ["src_addr", "dst_addr"], timeslot=False)
        top = topk_exact(batch, ["src_addr", "dst_addr"], k=10)
        assert len(top["bytes"]) == 10
        assert (np.diff(top["bytes"].astype(np.int64)) <= 0).all()
        assert top["bytes"][0] == full["bytes"].max()
