"""Ingest runtime: sharded grouping oracle equivalence, pipelined
executor backpressure + drain/stop, async flusher error propagation.

The contracts under test are the ones the dataplane's correctness hangs
on: (1) sharded and native grouping are OUTPUT-IDENTICAL to the serial
numpy groupby (hash-prefix shards concatenate into global hash order);
(2) the executor's bounded queue really bounds (backpressure, no
dropping, order preserved) and its idle protocol never abandons a tail;
(3) a pipelined worker produces byte-identical sink rows to the serial
worker, open windows included (drain-on-stop); (4) a background flush
failure fails the STEP — before its offsets commit — instead of
silently dropping rows.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from flow_pipeline_tpu import native
from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.engine.hostfused import HostGroupPipeline
from flow_pipeline_tpu.ingest import (
    AsyncFlusher,
    FlushError,
    PipelinedExecutor,
    ShardPool,
    group_by_key_sharded,
)
from flow_pipeline_tpu.ingest import shard as shard_mod
from flow_pipeline_tpu.ops import hostgroup
from flow_pipeline_tpu.schema import wire
from flow_pipeline_tpu.transport import Consumer, InProcessBus

from test_fused import BS, WINDOW, canon_rows, make_models, make_stream


@pytest.fixture(scope="module")
def pool():
    with ShardPool(workers=4) as p:
        yield p


class TestShardedGrouping:
    def _random(self, rng, n, w=5):
        lanes = rng.integers(0, 40, size=(n, w)).astype(np.uint32)
        planes = [rng.integers(0, 100, size=(n, 3)).astype(np.float32),
                  rng.integers(0, 100, size=n).astype(np.uint64)]
        return lanes, planes

    @pytest.mark.parametrize("exact", [True, False])
    @pytest.mark.parametrize("n", [0, 7, 9000, 20000])
    def test_matches_serial_bitwise(self, rng, pool, exact, n,
                                    monkeypatch):
        """Hash-prefix shards concatenate into exactly the serial result
        — same group order, same sums — for any batch size."""
        monkeypatch.setattr(shard_mod, "MIN_SHARD_ROWS", 4)
        lanes, planes = self._random(rng, n)
        su, ss, sc = hostgroup.group_by_key(lanes, planes, exact)
        pu, ps, pc = group_by_key_sharded(lanes, planes, pool, shards=4,
                                          exact=exact)
        np.testing.assert_array_equal(su, pu)
        np.testing.assert_array_equal(sc, pc)
        for a, b in zip(ss, ps):
            np.testing.assert_array_equal(a, b)

    def test_exact_collision_fallback_survives_sharding(self, rng, pool,
                                                        monkeypatch):
        """A forced full-hash collision lands both keys in the SAME shard
        (identical hashes share every prefix), where the per-shard verify
        regroups lexicographically — sharded stays exact."""
        monkeypatch.setattr(shard_mod, "MIN_SHARD_ROWS", 4)
        monkeypatch.setattr(
            hostgroup, "hash_u64",
            lambda lanes: np.zeros(lanes.shape[0], np.uint64))
        lanes = rng.integers(0, 5, size=(64, 2)).astype(np.uint32)
        vals = [rng.integers(0, 9, size=64).astype(np.uint64)]
        uniq, (s,), counts = group_by_key_sharded(lanes, vals, pool,
                                                  shards=4, exact=True)
        want: dict[tuple, int] = {}
        for i, row in enumerate(map(tuple, lanes)):
            want[row] = want.get(row, 0) + int(vals[0][i])
        assert len(uniq) == len(want)
        for i, row in enumerate(map(tuple, uniq)):
            assert s[i] == want[row]

    @pytest.mark.skipif(not native.group_available(),
                        reason="libflowdecode.so not built with hash_group")
    @pytest.mark.parametrize("exact", [True, False])
    def test_native_matches_numpy(self, rng, exact):
        """The C kernel computes the same hash, so group ORDER (not just
        content) matches the numpy path exactly."""
        lanes = rng.integers(0, 60, size=(5000, 7)).astype(np.uint32)
        planes = [rng.integers(0, 100, size=(5000, 2)).astype(np.float32)]
        nu, ns, nc = hostgroup.group_by_key(lanes, planes, exact)
        gu, gs, gc = hostgroup.group_by_key(lanes, planes, exact,
                                            native=True)
        np.testing.assert_array_equal(nu, gu)
        np.testing.assert_array_equal(nc, gc)
        np.testing.assert_array_equal(ns[0], gs[0])

    @pytest.mark.skipif(not native.group_available(),
                        reason="libflowdecode.so not built with hash_group")
    def test_native_kernel_contract(self, rng):
        lanes = rng.integers(0, 3, size=(257, 2)).astype(np.uint32)
        perm, starts, collided = native.hash_group(lanes)
        assert not collided
        assert sorted(perm.tolist()) == list(range(257))
        h = hostgroup.hash_u64(lanes)
        sh = h[perm]
        assert (np.diff(sh.astype(np.uint64)) >= 0).all()  # hash order
        assert starts[0] == 0 and len(starts) == len(np.unique(h))


class _ListConsumer:
    """Minimal consumer: a fixed batch list, then idle forever."""

    def __init__(self, batches):
        self.batches = list(batches)

    def poll(self, max_messages):
        return self.batches.pop(0) if self.batches else None


class TestPipelinedExecutor:
    def test_backpressure_bound_and_order(self):
        """The prepared queue never exceeds its cap while the consumer
        side lags, nothing is dropped, order is preserved."""
        batches = [[i] * 3 for i in range(20)]  # len() > 0 stands in
        ex = PipelinedExecutor(_ListConsumer(batches), prepare=tuple,
                               depth=2, idle_sleep=0.005)
        got = []
        first = ex.next()
        time.sleep(0.2)  # group thread runs ahead into the bound
        assert ex._out.qsize() <= 2
        got.append(first)
        while True:
            item = ex.next()
            if item is None:
                break
            got.append(item)
            assert ex._out.qsize() <= 2
        assert ex.high_water <= 2
        assert got == [tuple(b) for b in batches]
        assert ex.next() is None  # idle stays idle
        ex.stop()

    def test_prepare_error_propagates(self):
        def boom(batch):
            raise RuntimeError("poison batch")

        ex = PipelinedExecutor(_ListConsumer([[1]]), prepare=boom,
                               idle_sleep=0.005)
        with pytest.raises(RuntimeError, match="poison"):
            ex.next()

    def test_poll_error_propagates(self):
        class Bad:
            def poll(self, n):
                raise OSError("broker gone")

        ex = PipelinedExecutor(Bad(), prepare=tuple, idle_sleep=0.005)
        with pytest.raises(OSError, match="broker gone"):
            ex.next()


class TestAsyncFlusher:
    def test_jobs_run_in_order_and_drain(self):
        f = AsyncFlusher(max_queue=4)
        out = []
        for i in range(10):
            f.submit(lambda i=i: out.append(i))
        f.drain()
        assert out == list(range(10))
        f.stop()

    def test_error_latches_and_fails_drain(self):
        f = AsyncFlusher(max_queue=4)
        f.submit(lambda: 1 / 0)
        with pytest.raises(FlushError):
            f.drain()
        f.submit(lambda: None)  # post-error submits work again
        f.drain()
        f.stop()


def _stream_to_bus(batches):
    bus = InProcessBus()
    bus.create_topic("flows", 1)
    for b in batches:
        for frame in wire.iter_raw_frames(b.to_wire()):
            bus.produce("flows", frame)
    return bus


class CollectSink:
    def __init__(self):
        self.rows: dict[str, list] = {}

    def write(self, table, rows):
        self.rows.setdefault(table, []).append(rows)


def _run_worker(mode, sink, **cfg_kw):
    bus = _stream_to_bus(make_stream())
    worker = StreamWorker(
        Consumer(bus, fixedlen=True),
        make_models(WINDOW, 100),
        [sink],
        WorkerConfig(poll_max=BS, snapshot_every=0, ingest_mode=mode,
                     **cfg_kw),
    )
    worker.run(stop_when_idle=True)
    return worker


class TestPipelinedWorker:
    @pytest.mark.parametrize("kw", [
        {},
        {"ingest_native_group": True},
        {"ingest_shards": 4},
    ])
    def test_sink_rows_match_serial(self, kw):
        """Drain-on-stop oracle: the pipelined worker (in every grouping
        backend) lands the same rows as the serial one for every table,
        open windows included — nothing stuck in a queue at shutdown."""
        serial, pipelined = CollectSink(), CollectSink()
        ws = _run_worker("serial", serial)
        wp = _run_worker("pipelined", pipelined, **kw)
        assert ws.fused is not None and wp.fused is not None
        assert isinstance(wp.fused, HostGroupPipeline)
        assert wp.executor is not None and wp.flusher is not None
        assert set(serial.rows) == set(pipelined.rows)
        f5_s = sorted(sum([canon_rows(r)
                           for r in serial.rows["flows_5m"]], []))
        f5_p = sorted(sum([canon_rows(r)
                           for r in pipelined.rows["flows_5m"]], []))
        assert f5_s == f5_p
        for table in ("top_talkers", "top_src_ips", "top_dst_ips",
                      "top_src_ports"):
            a = serial.rows[table]
            b = pipelined.rows[table]
            assert len(a) == len(b)
            for ra, rb in zip(a, b):
                assert ra.keys() == rb.keys()
                for k in ra:
                    np.testing.assert_array_equal(np.asarray(ra[k]),
                                                  np.asarray(rb[k]))

    def test_flusher_error_fails_step_before_commit(self):
        """A sink failure on the background flusher must surface as a
        FlushError on the worker thread BEFORE offsets commit — rows are
        replayed, not dropped."""
        class FailingSink:
            def write(self, table, rows):
                raise IOError("disk full")

        bus = _stream_to_bus(make_stream())
        consumer = Consumer(bus, fixedlen=True)
        worker = StreamWorker(
            consumer, make_models(WINDOW, 100), [FailingSink()],
            WorkerConfig(poll_max=BS, snapshot_every=0,
                         ingest_mode="pipelined"),
        )
        assert worker.flusher is not None
        with pytest.raises(FlushError):
            worker.run(stop_when_idle=True)
        # nothing was committed past the first flush failure
        assert consumer.committed(0) == 0

    def test_queue_depth_bounded_end_to_end(self):
        sink = CollectSink()
        w = _run_worker("pipelined", sink, ingest_depth=2)
        assert w.executor.high_water <= 2
