"""Host-grouped pipeline equivalence (engine.hostfused vs engine.fused).

The CPU-backend pipeline regroups batches on the host with numpy and
ships compact group tables to a single jitted state-update step; it must
be output-identical to the device-sorted fused pipeline (which is itself
equivalence-tested against the serial per-model path in test_fused.py):
same flows_5m rows bit-for-bit, same top-K tables, same DDoS alerts,
same late-row drops — window boundaries and late data included.

ops.hostgroup's groupby is additionally property-tested against a dict
oracle, with hash collisions FORCED (constant hash) to exercise the
lexicographic fallback and the exact=False merge semantics.
"""

from __future__ import annotations

import numpy as np
import pytest

from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.engine.fused import FusedPipeline
from flow_pipeline_tpu.engine.hostfused import HostGroupPipeline
from flow_pipeline_tpu.models import WindowAggConfig, WindowAggregator
from flow_pipeline_tpu.ops import hostgroup
from flow_pipeline_tpu.schema import wire
from flow_pipeline_tpu.transport import Consumer, InProcessBus

from test_fused import (
    BS,
    WINDOW,
    assert_same_windows,
    canon_rows,
    make_models,
    make_stream,
)


class TestGroupByKey:
    def _oracle(self, lanes, planes):
        acc: dict[tuple, list] = {}
        for i, row in enumerate(map(tuple, lanes)):
            slot = acc.setdefault(row, [0] + [np.zeros(p.shape[1:]
                                   if p.ndim > 1 else ()) for p in planes])
            slot[0] += 1
            for j, p in enumerate(planes):
                slot[j + 1] = slot[j + 1] + p[i].astype(np.float64)
        return acc

    def test_matches_dict_oracle(self, rng):
        lanes = rng.integers(0, 7, size=(300, 3)).astype(np.uint32)
        pf = rng.integers(0, 50, size=(300, 2)).astype(np.float32)
        pu = rng.integers(0, 50, size=300).astype(np.uint64)
        uniq, (sf, su), counts = hostgroup.group_by_key(lanes, [pf, pu])
        want = self._oracle(lanes, [pf, pu])
        assert len(uniq) == len(want)
        for i, row in enumerate(map(tuple, uniq)):
            cnt, wf, wu = want[row]
            assert counts[i] == cnt
            np.testing.assert_array_equal(sf[i], wf)
            np.testing.assert_array_equal(su[i], wu)

    def test_forced_collision_stays_exact(self, rng, monkeypatch):
        """A constant hash makes EVERY distinct key a collision; the
        verify pass must detect it and regroup lexicographically."""
        monkeypatch.setattr(
            hostgroup, "hash_u64",
            lambda lanes: np.zeros(lanes.shape[0], np.uint64))
        lanes = rng.integers(0, 5, size=(64, 2)).astype(np.uint32)
        vals = rng.integers(0, 9, size=64).astype(np.uint64)
        uniq, (s,), counts = hostgroup.group_by_key(lanes, [vals])
        want = self._oracle(lanes, [vals])
        assert len(uniq) == len(want)
        for i, row in enumerate(map(tuple, uniq)):
            assert s[i] == want[row][1]

    def test_exact_false_merges_on_collision(self, rng, monkeypatch):
        """exact=False skips the verify: a full-hash collision merges the
        tuples into one group — the documented sketch-path trade."""
        monkeypatch.setattr(
            hostgroup, "hash_u64",
            lambda lanes: np.zeros(lanes.shape[0], np.uint64))
        lanes = rng.integers(0, 5, size=(64, 2)).astype(np.uint32)
        vals = np.ones(64, np.float32)
        uniq, (s,), counts = hostgroup.group_by_key(lanes, [vals],
                                                    exact=False)
        assert len(uniq) == 1
        assert s[0] == 64.0

    def test_empty_input(self):
        uniq, (s,), counts = hostgroup.group_by_key(
            np.zeros((0, 2), np.uint32), [np.zeros(0, np.float32)])
        assert uniq.shape == (0, 2) and len(counts) == 0

    def test_select_lanes(self):
        widths = {"src_addr": 4, "dst_addr": 4, "src_port": 1, "proto": 1}
        key_cols = ("src_addr", "dst_addr", "src_port", "proto")
        assert hostgroup.select_lanes(key_cols, widths, ("dst_addr",)) == \
            [4, 5, 6, 7]
        assert hostgroup.select_lanes(key_cols, widths,
                                      ("proto", "src_addr")) == \
            [9, 0, 1, 2, 3]
        with pytest.raises(KeyError):
            hostgroup.select_lanes(key_cols, widths, ("dst_port",))


def drive(pipeline_cls, models, batches):
    pipe = pipeline_cls(models)
    for b in batches:
        pipe.update(b)
    return models


class TestHostFusedEquivalence:
    def test_bit_exact_vs_fused(self):
        """Aligned cadence, integer values below 2^24: the host f64
        groupby sums cast to f32 without rounding, so every family —
        flows_5m, sketch tables, CMS estimates, dense ports, DDoS
        alerts, late-row drops — must match the device-sorted fused
        pipeline bit-for-bit."""
        batches = make_stream()
        fused = drive(FusedPipeline, make_models(WINDOW, 100), batches)
        host = drive(HostGroupPipeline, make_models(WINDOW, 100), batches)

        assert canon_rows(fused["flows_5m"].flush(True)) == \
            canon_rows(host["flows_5m"].flush(True))
        for name in ("top_talkers", "top_src_ips", "top_dst_ips",
                     "top_src_ports"):
            assert_same_windows(fused[name].flush(True),
                                host[name].flush(True))
            assert fused[name].late_flows_dropped == \
                host[name].late_flows_dropped
        fa, ha = fused["ddos_alerts"], host["ddos_alerts"]
        assert fa.late_flows_dropped == ha.late_flows_dropped
        assert len(fa.alerts) == len(ha.alerts)
        for x, y in zip(fa.alerts, ha.alerts):
            assert x.keys() == y.keys()
            for k in x:
                np.testing.assert_array_equal(np.asarray(x[k]),
                                              np.asarray(y[k]))

    def test_cascade_plan_default_models(self):
        """The default model family must plan src/dst IP regroups off the
        5-tuple table and ride the DDoS accumulate on the dst family."""
        pipe = HostGroupPipeline(make_models(WINDOW, 100))
        plans = dict(zip([n for n, _ in pipe._hh], pipe._fam_plan))
        assert plans["top_talkers"] == ("own",)
        assert plans["top_src_ips"][0] == "cascade"
        assert plans["top_dst_ips"][0] == "cascade"
        assert pipe._ddos_plan is not None
        assert pipe._ddos_plan[0] == "cascade"

    def test_flows5m_pending_rows_cover_snapshot_drain(self):
        """Host rows are deferred; a drain (snapshot/flush path) must fold
        them — no rows may be lost between chunks and a checkpoint."""
        agg = WindowAggregator(WindowAggConfig(batch_size=BS))
        # key layout: [timeslot, *key lanes, sampling_rate] — the rate is a
        # mandatory last store-key lane under the default scale_col
        keys = np.array([[6000, 1, 2, 3, 10], [6000, 1, 2, 3, 10]],
                        np.uint32)
        sums = np.array([[10, 1], [5, 2]], np.uint64)
        agg.add_host_rows(keys, sums, np.array([1, 1]))
        assert agg._pending_host  # still queued
        agg.watermark = 10_000
        rows = agg.flush(force=True)
        assert rows["bytes"].tolist() == [15]
        assert rows["packets"].tolist() == [3]
        assert rows["count"].tolist() == [2]
        assert rows["bytes_scaled"].tolist() == [150]  # sum * rate 10
        assert rows["packets_scaled"].tolist() == [30]

    def test_add_host_rows_rejects_wrong_key_width(self):
        """Ingest fails fast on a pre-r4 key layout (no rate lane) instead
        of silently consuming a key lane as the rate (ADVICE r4)."""
        agg = WindowAggregator(WindowAggConfig(batch_size=BS))
        keys = np.array([[6000, 1, 2, 3]], np.uint32)  # missing rate lane
        with pytest.raises(ValueError, match="add_host_rows"):
            agg.add_host_rows(keys, np.array([[10, 1]], np.uint64),
                              np.array([1]))

    def test_flows5m_unscaled_config_still_emits_scaled_cols(self):
        """scale_col=None must emit *_scaled == raw sums, not drop the
        columns — the sink schema is fixed and NULL scaled columns would
        silently blank sum(bytes_scaled) panels (ADVICE r4)."""
        agg = WindowAggregator(WindowAggConfig(batch_size=BS,
                                               scale_col=None))
        keys = np.array([[6000, 1, 2, 3]], np.uint32)  # no rate lane
        agg.add_host_rows(keys, np.array([[10, 1]], np.uint64),
                          np.array([2]))
        agg.watermark = 10_000
        rows = agg.flush(force=True)
        assert rows["bytes_scaled"].tolist() == rows["bytes"].tolist() == [10]
        assert rows["packets_scaled"].tolist() == [1]

    def test_eligible_modes(self):
        assert HostGroupPipeline.eligible("on")
        assert not HostGroupPipeline.eligible("off")
        # tests force the CPU backend (conftest), so auto must pick it
        assert HostGroupPipeline.eligible("auto")
        with pytest.raises(ValueError):  # typos must not silently mean auto
            HostGroupPipeline.eligible("true")


def test_worker_host_assist_vs_device_sink_rows():
    """Integration: the same stream through host_assist on/off workers
    lands identical flows_5m rows in the sink."""
    class CollectSink:
        def __init__(self):
            self.rows: dict[str, list] = {}

        def write(self, table, rows):
            self.rows.setdefault(table, []).append(rows)

    out = {}
    for assist in ("on", "off"):
        bus = InProcessBus()
        bus.create_topic("flows", 1)
        for b in make_stream():
            for frame in wire.iter_raw_frames(b.to_wire()):
                bus.produce("flows", frame)
        sink = CollectSink()
        worker = StreamWorker(
            Consumer(bus, fixedlen=True),
            make_models(WINDOW, 100),
            [sink],
            WorkerConfig(poll_max=BS, snapshot_every=0, host_assist=assist),
        )
        assert isinstance(worker.fused, HostGroupPipeline) == (assist == "on")
        worker.run(stop_when_idle=True)
        rows = [canon_rows(r) for r in sink.rows.get("flows_5m", [])]
        out[assist] = sorted(sum(rows, []))
    assert out["on"] == out["off"]


# ---- native lane builders (r19 flowspeed) ----------------------------------
#
# ff_build_lanes / ff_build_planes consume the decoded columns directly
# and must be BIT-EXACT twins of the numpy builders they replace
# (_key_lanes_into / _value_planes_np / the wagg lane fill) — u64
# saturation, u32->f32 rounding, the f32 scale multiply and the wagg
# slot transform included — at every thread count. The numpy bodies
# stay as the fallback for a pre-r19 library, so the pipeline-level
# test drives both and compares whole model outputs.


from flow_pipeline_tpu import native as _native  # noqa: E402


@pytest.mark.skipif(
    not _native.lanes_available(),
    reason="libflowdecode lacks the lane builders; run `make native`")
class TestLaneBuilders:
    def _cols(self, rng, n=6000):
        """A decoded-column dict covering every lane shape: scalar u32,
        [n, 4] address words, and u64 columns with values PAST the u32
        saturation point (the edge _u32_lane clamps)."""
        big = rng.integers(0, 1 << 40, size=n, dtype=np.uint64)
        big[:8] = [0, 1, 0xFFFFFFFF, 0x100000000, (1 << 64) - 1,
                   0xFFFFFFFE, 0x100000001, 42]
        return {
            "proto": rng.integers(0, 256, size=n).astype(np.uint32),
            "src_port": rng.integers(0, 1 << 16, size=n).astype(np.uint32),
            "src_addr": rng.integers(0, 1 << 32, size=(n, 4),
                                     dtype=np.uint64).astype(np.uint32),
            "bytes": big,
            "packets": rng.integers(0, 1 << 34, size=n, dtype=np.uint64),
            "sampling_rate": rng.integers(0, 4, size=n, dtype=np.uint64),
            "time_received": rng.integers(0, 1 << 33, size=n,
                                          dtype=np.uint64),
        }

    @pytest.mark.parametrize("threads", [1, 2, 8])
    @pytest.mark.parametrize("key_cols", [
        ("proto",), ("src_addr",), ("proto", "src_port", "src_addr"),
        ("src_addr", "bytes", "proto")])
    def test_key_lanes_match_numpy_twin(self, rng, threads, key_cols):
        from flow_pipeline_tpu import native
        from flow_pipeline_tpu.engine.hostfused import _key_lanes_into

        cols = self._cols(rng)
        got = native.build_lanes([cols[c] for c in key_cols],
                                 threads=threads)
        np.testing.assert_array_equal(got, _key_lanes_into(cols, key_cols))

    @pytest.mark.parametrize("threads", [1, 2, 8])
    def test_wagg_lanes_slot_transform(self, rng, threads):
        """The wagg layout: the slot lane is time_received saturated
        then snapped to the window boundary (v - v % window), followed
        by key lanes and the rate lane — one native pass vs the numpy
        fill."""
        from flow_pipeline_tpu import native

        cols = self._cols(rng)
        window = 300
        got = native.build_lanes(
            [cols["time_received"], cols["proto"], cols["src_addr"],
             cols["sampling_rate"]],
            mods=[window, 0, 0, 0], threads=threads)
        t = np.minimum(cols["time_received"],
                       np.uint64(0xFFFFFFFF)).astype(np.uint32)
        slot = t - t % np.uint32(window)
        want = np.concatenate(
            [slot[:, None], cols["proto"][:, None], cols["src_addr"],
             np.minimum(cols["sampling_rate"],
                        np.uint64(0xFFFFFFFF)).astype(np.uint32)[:, None]],
            axis=1)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("threads", [1, 2, 8])
    @pytest.mark.parametrize("scale", [None, "sampling_rate"])
    def test_value_planes_f32_match_numpy_twin(self, rng, threads, scale):
        from flow_pipeline_tpu import native
        from flow_pipeline_tpu.engine.hostfused import _value_planes_np

        cols = self._cols(rng)
        value_cols = ("bytes", "packets")
        got = native.build_planes_f32(
            [cols[c] for c in value_cols],
            scale=cols[scale] if scale else None, threads=threads)
        want = _value_planes_np(cols, value_cols, scale)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("threads", [1, 2, 8])
    def test_value_planes_u64_match_wagg_twin(self, rng, threads):
        from flow_pipeline_tpu import native

        cols = self._cols(rng)
        value_cols = ("bytes", "packets")
        got = native.build_planes_u64([cols[c] for c in value_cols],
                                      threads=threads)
        want = np.stack([np.minimum(cols[c], np.uint64(0xFFFFFFFF))
                         for c in value_cols], axis=1)
        np.testing.assert_array_equal(got, want)

    def test_empty_batch(self):
        from flow_pipeline_tpu import native

        out = native.build_lanes([np.zeros(0, np.uint32),
                                  np.zeros((0, 4), np.uint32)])
        assert out.shape == (0, 5)
        assert native.build_planes_u64([np.zeros(0, np.uint64)]).shape \
            == (0, 1)

    def test_pipeline_native_vs_numpy_lanes(self):
        """Whole-model parity: the same stream through the host sketch
        pipeline with native lane building live vs forced onto the
        numpy fallback — identical windows, tables and alerts (the
        degradation path IS the bit-exact twin)."""
        from flow_pipeline_tpu.hostsketch import HostSketchPipeline

        def run(native_lanes: bool):
            models = make_models(WINDOW, 100)
            pipe = HostSketchPipeline(models)
            if not native_lanes:
                pipe._native_lanes = False
            else:
                assert pipe._native_lanes, "lane builders not live"
            for b in make_stream():
                pipe.update(b)
            pipe.sync_states()
            return models

        # flush-compare: flows_5m rows bit-for-bit, every hh family's
        # windows
        a, b = run(True), run(False)
        assert canon_rows(a["flows_5m"].flush(True)) == \
            canon_rows(b["flows_5m"].flush(True))
        for name in ("top_talkers", "top_src_ips", "top_dst_ips"):
            assert_same_windows(a[name].flush(True), b[name].flush(True))
