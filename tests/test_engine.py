"""Engine tests: worker E2E over the bus, offset protocol, checkpoint
save/restore, and the kill-worker-mid-window fault injection from
SURVEY.md §5/§10 (resume without loss or double counting)."""

import numpy as np
import pytest

from flow_pipeline_tpu.engine import (
    StreamWorker,
    WindowedHeavyHitter,
    WorkerConfig,
)
from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile, ZipfProfile
from flow_pipeline_tpu.models import (
    DDoSConfig,
    DDoSDetector,
    HeavyHitterConfig,
    WindowAggConfig,
    WindowAggregator,
)
from flow_pipeline_tpu.models.oracle import flows_5m
from flow_pipeline_tpu.schema.batch import FlowBatch
from flow_pipeline_tpu.sink import MemorySink
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer


def fill_bus(n=4000, seed=61, rate=20.0, partitions=2):
    bus = InProcessBus()
    bus.create_topic("flows", partitions)
    gen = FlowGenerator(MockerProfile(), seed=seed, t0=1_699_999_800, rate=rate)
    batches = []
    prod = Producer(bus, fixedlen=True)
    for _ in range(n // 500):
        b = gen.batch(500)
        batches.append(b)
        prod.send_many(b.to_messages())
    return bus, FlowBatch.concat(batches)


def make_worker(bus, checkpoint=None, snapshot_every=3, batch_size=512):
    consumer = Consumer(bus, fixedlen=True)
    models = {
        "flows_5m": WindowAggregator(WindowAggConfig(batch_size=batch_size)),
        "top_talkers": WindowedHeavyHitter(
            HeavyHitterConfig(batch_size=batch_size, width=1 << 12, capacity=64),
            k=10,
        ),
    }
    sink = MemorySink()
    worker = StreamWorker(
        consumer, models, [sink],
        WorkerConfig(poll_max=batch_size, snapshot_every=snapshot_every,
                     checkpoint_path=checkpoint),
    )
    return worker, sink


def flows5m_totals(sink):
    rows = sink.tables.get("flows_5m", [])
    agg = {}
    for r in rows:  # merge partial rows (late-data contract)
        key = (r["timeslot"], r["src_as"], r["dst_as"], r["etype"])
        b, p, c = agg.get(key, (0, 0, 0))
        agg[key] = (b + r["bytes"], p + r["packets"], c + r["count"])
    return agg


def assert_matches_oracle(got, all_flows):
    """Merged (window, key) sink totals must equal the exact oracle."""
    oracle = flows_5m(all_flows)
    assert len(got) == len(oracle["timeslot"])
    for i in range(len(oracle["timeslot"])):
        key = (int(oracle["timeslot"][i]), int(oracle["src_as"][i]),
               int(oracle["dst_as"][i]), int(oracle["etype"][i]))
        assert got[key] == (int(oracle["bytes"][i]),
                            int(oracle["packets"][i]),
                            int(oracle["count"][i]))


class TestWorkerE2E:
    def test_bus_to_sink_parity(self):
        bus, all_flows = fill_bus()
        worker, sink = make_worker(bus)
        worker.run(stop_when_idle=True)
        assert_matches_oracle(flows5m_totals(sink), all_flows)
        # top talkers emitted per closed window
        assert "top_talkers" in sink.tables

    def test_offsets_committed_after_drain(self):
        bus, _ = fill_bus(n=2000)
        worker, _ = make_worker(bus)
        worker.run(stop_when_idle=True)
        assert worker.consumer.lag() == 0

    def test_metrics_incremented(self):
        bus, _ = fill_bus(n=1000)
        worker, _ = make_worker(bus)
        worker.run(stop_when_idle=True)
        assert worker.m_flows.value() >= 1000
        assert worker.m_rows.value() > 0  # insert_count actually increments


class TestCheckpointResume:
    def test_snapshot_roundtrip(self, tmp_path):
        from flow_pipeline_tpu.engine.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        state = {
            "covered": {"0": 17},
            "windows": {1699999800: {(65000, 65001): np.array([1, 2, 3],
                                                              np.uint64)}},
            "scalar": 5,
            "none": None,
        }
        path = str(tmp_path / "ckpt")
        save_checkpoint(path, state)
        save_checkpoint(path, state)  # overwrite must be atomic + idempotent
        got = load_checkpoint(path)
        assert got["covered"] == {"0": 17}
        assert got["scalar"] == 5 and got["none"] is None
        inner = got["windows"][1699999800][(65000, 65001)]
        np.testing.assert_array_equal(inner, [1, 2, 3])

    def test_kill_mid_window_resume_no_loss_no_double(self, tmp_path):
        """Fault injection: worker dies between snapshots; a fresh worker
        restores and the merged output still matches the oracle exactly."""
        bus, all_flows = fill_bus(n=4000)
        ckpt = str(tmp_path / "ckpt")

        w1, sink1 = make_worker(bus, checkpoint=ckpt, snapshot_every=2)
        for _ in range(3):  # a few batches, at least one snapshot...
            w1.run_once()
        # ... then CRASH (no finalize, no final snapshot/commit)
        del w1

        w2, sink2 = make_worker(bus, checkpoint=ckpt, snapshot_every=2)
        assert w2.restore()
        w2.run(stop_when_idle=True)

        # combine what sink1 flushed before the crash with sink2's output
        combined = MemorySink()
        combined.tables = {
            k: list(v) for k, v in sink1.tables.items()
        }
        for k, v in sink2.tables.items():
            combined.tables.setdefault(k, []).extend(v)
        assert_matches_oracle(flows5m_totals(combined), all_flows)

    def test_flush_triggers_snapshot(self, tmp_path):
        # any flush that emitted rows must immediately snapshot+commit, not
        # wait for the snapshot_every cadence (re-emission exposure)
        import os

        bus, _ = fill_bus(n=4000, rate=10.0)  # 400s -> a window closes mid-run
        ckpt = str(tmp_path / "ckpt")
        worker, sink = make_worker(bus, checkpoint=ckpt, snapshot_every=10**9)
        while worker.run_once():
            if sink.tables.get("flows_5m"):
                break
        assert sink.tables.get("flows_5m"), "test premise: a window must close"
        assert os.path.isdir(ckpt), "snapshot must follow the first emission"
        assert worker._emitted_since_snapshot is False

    def test_old_checkpoint_fallback(self, tmp_path):
        # crash between save_checkpoint's two renames leaves only .old;
        # load/restore must fall back to it
        import os

        from flow_pipeline_tpu.engine.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        path = str(tmp_path / "ckpt")
        save_checkpoint(path, {"v": 1})
        os.rename(path, path + ".old")  # simulate mid-rename crash
        assert load_checkpoint(path)["v"] == 1

    def test_restore_missing_returns_false(self, tmp_path):
        bus, _ = fill_bus(n=500)
        worker, _ = make_worker(bus, checkpoint=str(tmp_path / "nope"))
        assert worker.restore() is False


class TestSupervisedRecovery:
    def test_flaky_sink_supervised_exact_totals(self, tmp_path):
        """Full recovery chain: a sink that dies on its first flush kills
        the worker; the supervisor rebuilds one that restores the
        checkpoint and resumes from committed offsets. The failed flush
        never reached good_sink, so this proves replay-after-crash produces
        the exact oracle totals (cross-restart partial-row merging is
        covered by test_kill_mid_window_resume_no_loss_no_double)."""
        from flow_pipeline_tpu.engine import Supervisor, SupervisorConfig

        bus, all_flows = fill_bus(n=4000, rate=10.0)  # windows close mid-run
        ckpt = str(tmp_path / "ckpt")
        good_sink = MemorySink()
        failures = {"left": 1}

        class FlakySink:
            def write(self, table, rows):
                if failures["left"] > 0:
                    failures["left"] -= 1
                    raise ConnectionError("sink hiccup")
                good_sink.write(table, rows)

        def factory():
            worker, _ = make_worker(bus, checkpoint=ckpt, snapshot_every=2)
            worker.sinks = [FlakySink()]
            worker.restore()
            return worker

        Supervisor(factory, SupervisorConfig(backoff_initial=0.01),
                   stop_when_idle=True).run()
        assert failures["left"] == 0  # the crash actually happened
        assert_matches_oracle(flows5m_totals(good_sink), all_flows)


class TestDDoSInWorker:
    def test_alert_rows_reach_sink(self):
        bus = InProcessBus()
        bus.create_topic("flows", 1)
        gen = FlowGenerator(MockerProfile(), seed=71, t0=1_699_999_800,
                            rate=300.0)
        prod = Producer(bus, fixedlen=True)
        for i in range(9):
            b = gen.batch(3000)
            if i >= 7:
                hot = (b.columns["dst_addr"][:, 3] & 0xFF) == 5
                b.columns["packets"][hot] *= 60
            prod.send_many(b.to_messages())
        consumer = Consumer(bus, fixedlen=True)
        sink = MemorySink()
        worker = StreamWorker(
            consumer,
            {"ddos_alerts": DDoSDetector(DDoSConfig(batch_size=4096,
                                                    n_buckets=1 << 10))},
            [sink],
            WorkerConfig(poll_max=4096, snapshot_every=0),
        )
        worker.run(stop_when_idle=True)
        alerts = sink.tables.get("ddos_alerts", [])
        assert alerts, "attack must produce an alert row"
        assert any(a["dst_addr"].endswith(".0.0.5") or "::5" in a["dst_addr"]
                   or a["dst_addr"].endswith(":5") for a in alerts)


class TestRawArchive:
    """Opt-in flows_raw archiving (ref: compose/clickhouse/create.sh:36-62):
    the worker hands every consumed batch to sinks exposing archive_raw."""

    class ArchivingSink(MemorySink):
        def archive_raw(self, batch):
            from flow_pipeline_tpu.sink.clickhouse import raw_records

            recs = raw_records(batch)
            self.tables.setdefault("flows_raw", []).extend(recs)
            return len(recs)

    def run_worker(self, archive: bool):
        bus, all_flows = fill_bus(n=1000)
        consumer = Consumer(bus, fixedlen=True)
        sink = self.ArchivingSink()
        worker = StreamWorker(
            consumer,
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=512))},
            [sink],
            WorkerConfig(poll_max=512, archive_raw=archive),
        )
        worker.run(stop_when_idle=True)
        return worker, sink, all_flows

    def test_disabled_by_default_archives_nothing(self):
        _, sink, _ = self.run_worker(archive=False)
        assert "flows_raw" not in sink.tables

    def test_every_flow_archived_full_fidelity(self):
        worker, sink, all_flows = self.run_worker(archive=True)
        rows = sink.tables["flows_raw"]
        assert len(rows) == len(all_flows)
        assert worker.m_raw.value() == len(all_flows)
        # spot-check full fidelity on the first flow, including exact
        # 16-byte address round-trip through the IPv6 text form
        import ipaddress

        from flow_pipeline_tpu.schema.batch import words_to_addr

        c = all_flows.columns
        r = rows[0]
        assert r["Bytes"] == int(c["bytes"][0])
        assert r["Packets"] == int(c["packets"][0])
        assert r["SrcAS"] == int(c["src_as"][0])
        assert r["TimeReceived"] == int(c["time_received"][0])
        assert (ipaddress.IPv6Address(r["SrcAddr"]).packed
                == words_to_addr(np.asarray(c["src_addr"][0], np.uint32)))
        assert (ipaddress.IPv6Address(r["DstAddr"]).packed
                == words_to_addr(np.asarray(c["dst_addr"][0], np.uint32)))
        # Date is MATERIALIZED server-side from TimeReceived, not shipped
        assert set(r) == {
            "TimeReceived", "TimeFlowStart", "SequenceNum",
            "SamplingRate", "SamplerAddress", "SrcAddr", "DstAddr",
            "SrcAS", "DstAS", "EType", "Proto", "SrcPort", "DstPort",
            "Bytes", "Packets",
        }

    def test_archive_forces_snapshot_commit(self):
        # raw rows have no merge dedup, so every archived batch must be
        # followed by an offset commit (duplicate window = one batch, not
        # snapshot_every batches)
        bus, _ = fill_bus(n=1000)
        consumer = Consumer(bus, fixedlen=True)
        sink = self.ArchivingSink()
        worker = StreamWorker(
            consumer,
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=512))},
            [sink],
            # snapshot_every=0: only the archive coupling can trigger commits
            WorkerConfig(poll_max=512, snapshot_every=0, archive_raw=True),
        )
        worker.run_once()
        # the one consumed batch's offsets are committed immediately
        assert worker._covered  # one partition consumed
        for p, next_off in worker._covered.items():
            assert consumer.committed(p) == next_off


class TestRestoreModelMismatch:
    def test_checkpoint_with_extra_model_skipped(self, tmp_path):
        # checkpoint written with a model that is later disabled must not
        # crash restore (e.g. -model.ports flipped off between runs)
        path = str(tmp_path / "ckpt")
        bus, _ = fill_bus(n=1000)
        worker, _ = make_worker(bus, checkpoint=path, snapshot_every=1)
        worker.run(stop_when_idle=True)

        consumer = Consumer(bus, fixedlen=True)
        slim = StreamWorker(
            consumer,
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=512))},
            [MemorySink()],
            WorkerConfig(poll_max=512, checkpoint_path=path),
        )
        assert slim.restore()  # top_talkers state present but unconfigured
        assert slim.batches_seen == worker.batches_seen


class TestMultiWorkerPartitionSplit:
    def test_two_workers_disjoint_partitions_sum_to_oracle(self):
        # the sarama consumer-group model (ref: inserter/inserter.go:
        # 238-256): scale-out is more workers on disjoint partition
        # subsets; their merged sink output must equal the exact oracle
        import threading

        bus, all_flows = fill_bus(n=4000, partitions=4)
        # shared sink: both workers append concurrently; MemorySink.write
        # is a single list.extend, atomic under the GIL
        sink = MemorySink()
        workers = []
        for part_set in ([0, 1], [2, 3]):
            consumer = Consumer(bus, fixedlen=True, partitions=part_set)
            workers.append(StreamWorker(
                consumer,
                {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=512))},
                [sink],
                WorkerConfig(poll_max=512),
            ))
        threads = [
            threading.Thread(target=w.run, kwargs={"stop_when_idle": True})
            for w in workers
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        assert_matches_oracle(flows5m_totals(sink), all_flows)
        # each worker committed exactly its own partitions
        for w, parts in zip(workers, ([0, 1], [2, 3])):
            assert sorted(w._covered) == parts
