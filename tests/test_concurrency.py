"""Concurrency stress: producer threads blasting the bus while the worker
consumes and the query API reads — totals must stay exact (SURVEY.md §5
race detection; the reference has a single RWMutex and no -race CI)."""

import threading
import time
import urllib.request

from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.engine.query_api import QueryServer
from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile
from flow_pipeline_tpu.models import WindowAggConfig, WindowAggregator
from flow_pipeline_tpu.sink import MemorySink
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer


class TestConcurrentPipeline:
    def test_producers_race_consumer_exact_totals(self):
        bus = InProcessBus()
        bus.create_topic("flows", 4)
        n_producers, per_producer = 4, 2000

        thread_errors = []

        def produce(seed):
            try:
                gen = FlowGenerator(MockerProfile(), seed=seed,
                                    t0=1_699_999_800, rate=100.0)
                prod = Producer(bus, fixedlen=True)
                for _ in range(per_producer // 500):
                    prod.send_many(gen.batch(500).to_messages())
            except Exception as e:  # noqa: BLE001 — surface in the assert
                thread_errors.append(e)

        worker = StreamWorker(
            Consumer(bus, fixedlen=True),
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=512))},
            [sink := MemorySink()],
            WorkerConfig(snapshot_every=0, idle_sleep=0.005),
        )
        threads = [threading.Thread(target=produce, args=(100 + i,))
                   for i in range(n_producers)]
        stop = threading.Event()

        def consume():
            try:
                while not stop.is_set():  # churn while producers race us
                    if not worker.run_once():
                        time.sleep(0.001)  # caught up: don't starve producers
                while worker.run_once():  # then drain whatever remains
                    pass
                worker.finalize()
            except Exception as e:  # noqa: BLE001 — surface in the assert
                thread_errors.append(e)

        consumer_thread = threading.Thread(target=consume)
        consumer_thread.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        consumer_thread.join(timeout=60)
        assert not consumer_thread.is_alive()
        assert thread_errors == []

        total = sum(r["count"] for r in sink.tables.get("flows_5m", []))
        assert total == n_producers * per_producer
        assert worker.consumer.lag() == 0

    def test_queries_race_worker(self):
        bus = InProcessBus()
        bus.create_topic("flows", 2)
        gen = FlowGenerator(MockerProfile(), seed=7, t0=1_699_999_800,
                            rate=20.0)
        prod = Producer(bus, fixedlen=True)
        for _ in range(16):
            prod.send_many(gen.batch(500).to_messages())
        worker = StreamWorker(
            Consumer(bus, fixedlen=True),
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=512))},
            [sink := MemorySink()],
            WorkerConfig(snapshot_every=0),
        )
        worker.run_once()  # warm the jit before hammers: the first batch
        # holds the worker lock across compile, which could outlast a
        # conservative HTTP timeout on a cold runner
        server = QueryServer(worker, port=0).start()
        errors = []

        def hammer():
            for _ in range(50):
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{server.port}/windows", timeout=30
                    ).read()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        hammers = [threading.Thread(target=hammer) for _ in range(3)]
        for h in hammers:
            h.start()
        while worker.run_once():  # worker churns while queries hammer
            pass
        worker.finalize()
        for h in hammers:
            h.join()
        server.stop()
        assert errors == []
        total = sum(r["count"] for r in sink.tables.get("flows_5m", []))
        assert total == 8000
