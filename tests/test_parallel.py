"""Multi-chip tests on the virtual 8-device CPU mesh: sharded pipelines must
match single-chip results / the exact oracle (sketch merge is a monoid, so
sharding must not change answers beyond table-capacity effects)."""

import jax
import numpy as np
import pytest

from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile, ZipfProfile
from flow_pipeline_tpu.models import (
    HeavyHitterConfig,
    HeavyHitterModel,
    WindowAggConfig,
)
from flow_pipeline_tpu.models.oracle import flows_5m, topk_exact
from flow_pipeline_tpu.parallel import (
    ShardedHeavyHitter,
    ShardedWindowAggregator,
    make_mesh,
)
from flow_pipeline_tpu.schema.batch import FlowBatch


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh()


class TestShardedWindowAgg:
    def test_exact_parity_vs_oracle(self, mesh):
        g = FlowGenerator(MockerProfile(), seed=51, rate=40.0)
        batches = [g.batch(1000) for _ in range(8)]
        agg = ShardedWindowAggregator(WindowAggConfig(batch_size=256), mesh)
        for b in batches:
            agg.update(b)
        out = agg.flush(force=True)
        oracle = flows_5m(FlowBatch.concat(batches))
        assert len(out["timeslot"]) == len(oracle["timeslot"])
        got = {
            (int(t), int(s), int(d), int(e)): (int(b), int(c))
            for t, s, d, e, b, c in zip(
                out["timeslot"], out["src_as"], out["dst_as"], out["etype"],
                out["bytes"], out["count"],
            )
        }
        for i in range(len(oracle["timeslot"])):
            key = (int(oracle["timeslot"][i]), int(oracle["src_as"][i]),
                   int(oracle["dst_as"][i]), int(oracle["etype"][i]))
            assert got[key] == (int(oracle["bytes"][i]), int(oracle["count"][i]))

    def test_ragged_global_batch(self, mesh):
        # batch not divisible by n_dev * batch_size exercises padding
        g = FlowGenerator(MockerProfile(), seed=52, rate=100.0)
        agg = ShardedWindowAggregator(WindowAggConfig(batch_size=128), mesh)
        agg.update(g.batch(1000))  # 1000 < 8*128=1024
        out = agg.flush(force=True)
        assert int(out["count"].sum()) == 1000


class TestShardedHeavyHitter:
    def test_matches_single_chip_topk(self, mesh):
        config = HeavyHitterConfig(batch_size=512, width=1 << 13, capacity=256)
        g = FlowGenerator(ZipfProfile(n_keys=500, alpha=1.3), seed=53)
        batches = [g.batch(4096) for _ in range(4)]

        sharded = ShardedHeavyHitter(config, mesh)
        for b in batches:
            sharded.update(b)
        top_s = sharded.top(10)

        oracle = topk_exact(FlowBatch.concat(batches), ["src_addr", "dst_addr"], 10)
        for i in range(10):
            assert (top_s["src_addr"][i] == oracle["src_addr"][i]).all()
            assert (top_s["dst_addr"][i] == oracle["dst_addr"][i]).all()
            err = abs(float(top_s["bytes"][i]) - float(oracle["bytes"][i])) / float(
                oracle["bytes"][i]
            )
            assert err <= 0.01

    def test_cms_merge_is_exact_sum_of_shards(self, mesh):
        # psum-merged CMS must equal the single-chip CMS over the same stream
        config = HeavyHitterConfig(batch_size=512, width=1 << 12, capacity=64,
                                   conservative=False)  # linear -> exactly mergeable
        g = FlowGenerator(ZipfProfile(n_keys=100, alpha=1.2), seed=54)
        batch = g.batch(4096)

        sharded = ShardedHeavyHitter(config, mesh)
        sharded.update(batch)
        merged = sharded.merged_state()

        single = HeavyHitterModel(config)
        single.update(batch)

        np.testing.assert_allclose(
            np.asarray(merged.cms), np.asarray(single.state.cms), rtol=1e-6
        )

    def test_reset(self, mesh):
        config = HeavyHitterConfig(batch_size=256, width=1 << 10, capacity=32)
        m = ShardedHeavyHitter(config, mesh)
        g = FlowGenerator(ZipfProfile(n_keys=50), seed=55)
        m.update(g.batch(2048))
        m.reset()
        assert not m.top(5)["valid"].any()

    def test_sharded_ddos_detects_attack(self, mesh):
        from flow_pipeline_tpu.models import DDoSConfig
        from flow_pipeline_tpu.parallel import ShardedDDoSDetector

        det = ShardedDDoSDetector(
            DDoSConfig(batch_size=256, n_buckets=1 << 10,
                       sub_window_seconds=10),
            mesh,
        )
        g = FlowGenerator(MockerProfile(), seed=77, t0=1_699_999_800,
                          rate=300.0)
        for i in range(9):
            b = g.batch(3000)
            if i >= 7:
                hot = (b.columns["dst_addr"][:, 3] & 0xFF) == 13
                b.columns["packets"][hot] *= 60
            det.update(b)
        det.close_sub_window()
        assert det.alerts, "sharded detector must find the flood"
        assert any(int(a["dst_addr"][3]) & 0xFF == 13 for a in det.alerts)

    def test_sharded_ddos_quiet_on_steady(self, mesh):
        from flow_pipeline_tpu.models import DDoSConfig
        from flow_pipeline_tpu.parallel import ShardedDDoSDetector

        det = ShardedDDoSDetector(
            DDoSConfig(batch_size=256, n_buckets=1 << 10,
                       sub_window_seconds=10),
            mesh,
        )
        g = FlowGenerator(MockerProfile(), seed=78, t0=1_699_999_800,
                          rate=300.0)
        for _ in range(8):
            det.update(g.batch(3000))
        det.close_sub_window()
        assert det.alerts == []

    def test_sharded_hist_mass_stays_linear(self, mesh):
        # regression: psum'ing the replicated histogram at every close used
        # to multiply historical mass by n_dev per window (geometric blowup)
        import jax.numpy as jnp

        from flow_pipeline_tpu.models import DDoSConfig
        from flow_pipeline_tpu.models.ddos import DDoSDetector
        from flow_pipeline_tpu.parallel import ShardedDDoSDetector

        cfg = DDoSConfig(batch_size=256, n_buckets=256, sub_window_seconds=10)
        sharded = ShardedDDoSDetector(cfg, mesh)
        single = DDoSDetector(cfg)
        g1 = FlowGenerator(MockerProfile(), seed=81, t0=1_699_999_800,
                           rate=300.0)
        g2 = FlowGenerator(MockerProfile(), seed=81, t0=1_699_999_800,
                           rate=300.0)
        for _ in range(6):
            sharded.update(g1.batch(3000))
            single.update(g2.batch(3000))
        sharded.close_sub_window()
        single.close_sub_window()
        mass_sharded = float(jnp.sum(sharded.state.hist[0]))
        mass_single = float(jnp.sum(single.state.hist))
        assert mass_sharded == pytest.approx(mass_single, rel=1e-6)

    def test_witness_names_flood_not_big_single_flow(self, mesh):
        # SYN-flood shape: thousands of 1-packet flows to A sharing a bucket
        # with one larger benign flow to B -> witness must be A
        import numpy as np

        from flow_pipeline_tpu.models import DDoSConfig
        from flow_pipeline_tpu.models.ddos import DDoSDetector
        from flow_pipeline_tpu.ops.ewma import bucket_of
        from flow_pipeline_tpu.schema.batch import FlowBatch

        cfg = DDoSConfig(batch_size=512, n_buckets=64, sub_window_seconds=10,
                         warmup_windows=0)
        # find two distinct addrs in the same bucket
        cand = np.zeros((512, 4), dtype=np.uint32)
        cand[:, 3] = np.arange(512)
        b = np.asarray(bucket_of(cand, 64))
        dup = None
        for i in range(512):
            js = np.flatnonzero(b == b[i])
            if len(js) > 1:
                dup = (int(js[0]), int(js[1]))
                break
        assert dup is not None
        a_idx, b_idx = dup
        n = 401
        batch = FlowBatch.empty(n)
        batch.columns["time_received"][:] = 1_699_999_800
        batch.columns["packets"][:n - 1] = 1  # flood: 400 x 1 packet to A
        batch.columns["dst_addr"][: n - 1] = cand[a_idx]
        batch.columns["packets"][n - 1] = 50  # one benign 50-packet flow to B
        batch.columns["dst_addr"][n - 1] = cand[b_idx]
        det = DDoSDetector(cfg)
        det.update(batch)
        det.close_sub_window()
        addrs = np.asarray(det.state.addrs)
        assert addrs[b[a_idx]].tolist() == cand[a_idx].tolist()

    def test_submesh(self):
        # a 4-device mesh out of the 8 available
        mesh4 = make_mesh(4)
        config = HeavyHitterConfig(batch_size=256, width=1 << 10, capacity=32)
        m = ShardedHeavyHitter(config, mesh4)
        g = FlowGenerator(ZipfProfile(n_keys=50, alpha=1.5), seed=56)
        batch = g.batch(2048)
        m.update(batch)
        oracle = topk_exact(batch, ["src_addr", "dst_addr"], 1)
        top = m.top(1)
        assert (top["src_addr"][0] == oracle["src_addr"][0]).all()
