"""Schema core tests: wire codec round-trips, framing, columnar batches,
hashing parity (device vs numpy), and — when protoc/google.protobuf are
present — cross-validation against the canonical protobuf implementation."""

import shutil
import subprocess
import sys
import tempfile

import numpy as np
import pytest

from flow_pipeline_tpu.schema import (
    FlowMessage,
    FlowType,
    FlowBatch,
    encode_message,
    decode_message,
    encode_frame,
    decode_frames,
    encode_stream,
    hash_words,
    hash_columns,
)
from flow_pipeline_tpu.schema.keys import hash_words_np
from flow_pipeline_tpu.schema.batch import addr_to_words, words_to_addr


def sample_message(i=0):
    return FlowMessage(
        type=FlowType.SFLOW_5,
        time_received=1700000000 + i,
        sampling_rate=1000,
        sequence_num=42 + i,
        time_flow_start=1700000000 + i,
        time_flow_end=1700000001 + i,
        src_addr=bytes(range(16)),
        dst_addr=bytes(range(16, 32)),
        sampler_address=b"\x00" * 12 + b"\x0a\x00\x00\x01",
        bytes=1499,
        packets=99,
        src_as=65000,
        dst_as=65001,
        in_if=1,
        out_if=2,
        proto=6,
        src_port=443,
        dst_port=51234,
        ip_tos=0,
        forwarding_status=0,
        ip_ttl=64,
        tcp_flags=0x18,
        etype=0x86DD,
        icmp_type=0,
        icmp_code=0,
        ipv6_flow_label=12345,
        flow_direction=1,
    )


class TestWireCodec:
    def test_roundtrip(self):
        msg = sample_message()
        assert decode_message(encode_message(msg)) == msg

    def test_default_message_is_empty(self):
        assert encode_message(FlowMessage()) == b""
        assert decode_message(b"") == FlowMessage()

    def test_zero_fields_omitted(self):
        msg = FlowMessage(bytes=1)
        data = encode_message(msg)
        assert len(data) == 2  # one tag + one varint
        assert decode_message(data) == msg

    def test_large_varint(self):
        msg = FlowMessage(time_received=2**40)
        assert decode_message(encode_message(msg)).time_received == 2**40

    def test_unknown_fields_skipped(self):
        # field 12 (unused in schema) varint, then a known field
        extra = bytes([12 << 3, 7]) + encode_message(FlowMessage(packets=5))
        assert decode_message(extra).packets == 5

    def test_framing_roundtrip(self):
        msgs = [sample_message(i) for i in range(10)]
        data = encode_stream(msgs)
        assert decode_frames(data) == msgs

    def test_frame_single(self):
        msg = sample_message()
        frame = encode_frame(msg)
        body = encode_message(msg)
        assert frame[0] == len(body)  # small message: 1-byte varint prefix
        assert decode_frames(frame) == [msg]

    def test_truncated_frame_raises(self):
        data = encode_frame(sample_message())
        with pytest.raises(ValueError):
            decode_frames(data[:-1])

    def test_truncated_fixed_fields_raise(self):
        # unused field 12 with fixed32/fixed64 wire types, payload cut short
        with pytest.raises(ValueError):
            decode_message(bytes([(12 << 3) | 5, 0xAA, 0xBB]))
        with pytest.raises(ValueError):
            decode_message(bytes([(12 << 3) | 1, 0xAA]))


@pytest.mark.skipif(shutil.which("protoc") is None, reason="protoc not found")
class TestProtocCrossCheck:
    """Our codec vs the canonical implementation, via protoc codegen."""

    @pytest.fixture(scope="class")
    def pb2(self):
        pytest.importorskip("google.protobuf")
        import os

        proto_dir = os.path.join(
            os.path.dirname(__file__), "..", "flow_pipeline_tpu", "schema"
        )
        with tempfile.TemporaryDirectory() as td:
            r = subprocess.run(
                ["protoc", f"-I{proto_dir}", f"--python_out={td}", "flow.proto"],
                capture_output=True,
                text=True,
            )
            if r.returncode != 0:
                pytest.skip(f"protoc failed: {r.stderr}")
            sys.path.insert(0, td)
            try:
                import flow_pb2  # noqa

                yield flow_pb2
            finally:
                sys.path.remove(td)
                sys.modules.pop("flow_pb2", None)

    def test_decode_canonical_encoding(self, pb2):
        ours = sample_message()
        theirs = pb2.FlowMessage(
            Type=int(ours.type),
            TimeReceived=ours.time_received,
            SamplingRate=ours.sampling_rate,
            SequenceNum=ours.sequence_num,
            TimeFlowStart=ours.time_flow_start,
            TimeFlowEnd=ours.time_flow_end,
            SrcAddr=ours.src_addr,
            DstAddr=ours.dst_addr,
            SamplerAddress=ours.sampler_address,
            Bytes=ours.bytes,
            Packets=ours.packets,
            SrcAS=ours.src_as,
            DstAS=ours.dst_as,
            InIf=ours.in_if,
            OutIf=ours.out_if,
            Proto=ours.proto,
            SrcPort=ours.src_port,
            DstPort=ours.dst_port,
            IPTTL=ours.ip_ttl,
            TCPFlags=ours.tcp_flags,
            Etype=ours.etype,
            IPv6FlowLabel=ours.ipv6_flow_label,
            FlowDirection=ours.flow_direction,
        )
        assert decode_message(theirs.SerializeToString()) == ours

    def test_canonical_decodes_our_encoding(self, pb2):
        ours = sample_message()
        theirs = pb2.FlowMessage()
        theirs.ParseFromString(encode_message(ours))
        assert theirs.Bytes == ours.bytes
        assert theirs.SrcAddr == ours.src_addr
        assert theirs.Etype == ours.etype
        assert theirs.TimeFlowStart == ours.time_flow_start


class TestAddrWords:
    def test_roundtrip_16(self):
        addr = bytes(range(16))
        assert words_to_addr(addr_to_words(addr)) == addr

    def test_ipv4_lands_in_word3(self):
        # IPv4 embedded in trailing 4 bytes (collector convention)
        addr = b"\x00" * 12 + bytes([10, 1, 2, 3])
        words = addr_to_words(addr)
        assert words[3] == (10 << 24) | (1 << 16) | (2 << 8) | 3
        assert words[:3].sum() == 0

    def test_short_addr_left_padded(self):
        words = addr_to_words(bytes([10, 1, 2, 3]))
        assert words[3] == (10 << 24) | (1 << 16) | (2 << 8) | 3


class TestFlowBatch:
    def test_messages_roundtrip(self):
        msgs = [sample_message(i) for i in range(7)]
        batch = FlowBatch.from_messages(msgs)
        assert len(batch) == 7
        assert batch.to_messages() == msgs

    def test_from_wire(self):
        msgs = [sample_message(i) for i in range(5)]
        batch = FlowBatch.from_wire(encode_stream(msgs))
        assert batch.to_messages() == msgs

    def test_pad_to(self):
        batch = FlowBatch.from_messages([sample_message(i) for i in range(3)])
        padded, mask = batch.pad_to(8)
        assert len(padded) == 8
        assert mask.sum() == 3
        assert padded.columns["bytes"][3:].sum() == 0

    def test_slice_offsets(self):
        batch = FlowBatch.from_messages([sample_message(i) for i in range(10)])
        batch.first_offset, batch.last_offset = 100, 109
        s = batch.slice(2, 5)
        assert (s.first_offset, s.last_offset) == (102, 104)
        assert len(s) == 3

    def test_device_columns_int32(self):
        batch = FlowBatch.from_messages([sample_message()])
        dev = batch.device_columns()
        assert dev["bytes"].dtype == np.int32
        assert dev["src_addr"].shape == (1, 4)

    def test_uint64_fields_survive_host_and_saturate_on_device(self):
        m = FlowMessage(bytes=2**40, time_received=1700000000)
        batch = FlowBatch.from_messages([m])
        assert batch.columns["bytes"][0] == 2**40  # host keeps 64 bits
        dev = batch.device_columns(["bytes", "time_received"])
        assert dev["bytes"].view(np.uint32)[0] == 0xFFFFFFFF  # saturated
        assert dev["time_received"].view(np.uint32)[0] == 1700000000

    def test_oversized_varint_masks_not_crashes(self):
        # a peer sending >64-bit-looking values must not kill ingest
        m = FlowMessage(src_as=2**40 + 7)  # uint32 wire field, oversized
        batch = FlowBatch.from_messages([m])
        assert batch.columns["src_as"][0] == 7

    def test_concat(self):
        a = FlowBatch.from_messages([sample_message(0)])
        b = FlowBatch.from_messages([sample_message(1)])
        c = FlowBatch.concat([a, b])
        assert len(c) == 2
        assert c.to_messages() == [sample_message(0), sample_message(1)]


class TestHashing:
    def test_device_matches_numpy(self, rng):
        words = rng.integers(0, 2**32, size=(64, 9), dtype=np.uint32)
        dev = np.asarray(hash_words(words, seed=7))
        host = hash_words_np(words, seed=7)
        np.testing.assert_array_equal(dev.view(np.uint32), host)

    def test_seeds_decorrelate(self, rng):
        words = rng.integers(0, 2**32, size=(256, 2), dtype=np.uint32)
        h0 = np.asarray(hash_words(words, 0)).view(np.uint32)
        h1 = np.asarray(hash_words(words, 1)).view(np.uint32)
        assert (h0 == h1).mean() < 0.01

    def test_distribution_roughly_uniform(self, rng):
        words = rng.integers(0, 2**32, size=(20000, 1), dtype=np.uint32)
        h = np.asarray(hash_words(words)).view(np.uint32)
        buckets = np.bincount(h % 16, minlength=16)
        assert buckets.min() > 20000 / 16 * 0.8

    def test_hash_columns_addr_and_scalar(self, rng):
        n = 32
        cols = {
            "src_addr": rng.integers(0, 2**32, (n, 4), dtype=np.uint32).astype(np.int32),
            "proto": rng.integers(0, 256, n).astype(np.int32),
        }
        h = np.asarray(hash_columns(cols, ["src_addr", "proto"], seed=3))
        # equals hashing the concatenated 5 words
        words = np.concatenate(
            [cols["src_addr"].view(np.uint32), cols["proto"].view(np.uint32)[:, None]],
            axis=1,
        )
        np.testing.assert_array_equal(h.view(np.uint32), hash_words_np(words, 3))

    def test_known_murmur3_vector(self):
        # murmur3_x86_32(key=b"\x00\x00\x00\x00", seed=0) == 0x2362f9de
        h = hash_words_np(np.zeros((1, 1), dtype=np.uint32), 0)
        assert h[0] == 0x2362F9DE
