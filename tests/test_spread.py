"""flowspread tests: the distinct-count sketch family (models/spread.py,
ops/spread.py, hostsketch np_spread_*, native hs_spread_update).

The contracts pinned here, per docs/ARCHITECTURE.md "flowspread":

- three bit-exact twins: numpy reference, jnp ops kernel, threaded C —
  identical registers for any stream, any chunking, threads {1,2,8},
  u8-saturated planes included;
- mesh-exact merge: N-worker merged registers bit-identical to a single
  worker over the same stream at N in {1,2,4}, including a member
  restart-and-replay; decoded top rows identical; mixed-kind folds
  rejected;
- one decode: /query/spread through worker snapshot, delta-fed gateway
  state, and checkpoint restore answers from byte-identical registers;
- the sketchwatch spread audit (exact sampled SETS) reports relative
  error without perturbing the dataplane.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from flow_pipeline_tpu.engine import (StreamWorker, WindowedHeavyHitter,
                                      WorkerConfig)
from flow_pipeline_tpu.engine.hostfused import HostGroupPipeline
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.hostsketch.engine import (np_spread_query,
                                                 np_spread_update,
                                                 spread_apply_update)
from flow_pipeline_tpu.hostsketch.pipeline import HostSketchPipeline
from flow_pipeline_tpu.mesh import codec
from flow_pipeline_tpu.mesh import merge as merge_ops
from flow_pipeline_tpu.mesh.runtime import shard_ids
from flow_pipeline_tpu.models.scan import SCAN_MODEL, scan_model
from flow_pipeline_tpu.models.spread import (SpreadConfig, SpreadModel,
                                             spread_key_width,
                                             spread_top_from)
from flow_pipeline_tpu.models.superspreader import (SUPERSPREADER_MODEL,
                                                    superspreader_config,
                                                    superspreader_model)
from flow_pipeline_tpu.schema.batch import FlowBatch
from flow_pipeline_tpu.serve import ServeServer, attach_worker
from flow_pipeline_tpu.sink import MemorySink
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer

T0 = 1_699_999_800  # window-aligned stream start


def _zipf_batch(n=20_000, seed=7, t0=T0, rate=1e9):
    """One batch with spreader/scanner legs (all rows land in one
    5-minute window at the default rate)."""
    gen = FlowGenerator(ZipfProfile(n_keys=2000, spread_fraction=0.25),
                        seed=seed, t0=t0, rate=rate)
    return gen.batch(n)


def _pairs(n=4000, seed=0, kw=1, ew=1, key_space=50, elem_space=5000):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, key_space, (n, kw), dtype=np.uint32)
    elems = rng.integers(0, elem_space, (n, ew), dtype=np.uint32)
    return keys, elems


def _sub_batch(batch, mask):
    return FlowBatch({k: np.ascontiguousarray(v[mask])
                      for k, v in batch.columns.items()}, partition=0)


def _state_tuple(m):
    s = m.model.state
    return s.regs, s.table_keys, s.table_metric


def _assert_states_equal(a, b, tag=""):
    for x, y, name in zip(a, b, ("regs", "table_keys", "table_metric")):
        assert np.array_equal(x, y), f"{tag}{name}"


# ---- twins -----------------------------------------------------------------


class TestTwins:
    def test_numpy_vs_jnp_registers(self):
        from flow_pipeline_tpu.ops.spread import spread_update

        keys, elems = _pairs(seed=1)
        regs_np = np.zeros((2, 256, 64), np.uint8)
        np_spread_update(regs_np, keys, elems)
        import jax.numpy as jnp

        regs_j = np.asarray(
            spread_update(jnp.zeros((2, 256, 64), jnp.uint8), keys, elems),
            dtype=np.uint8)
        assert np.array_equal(regs_np, regs_j)

    def test_native_twin_thread_sweep(self):
        from flow_pipeline_tpu import native

        if not native.spread_available():
            pytest.skip("native library lacks hs_spread_update")
        keys, elems = _pairs(n=20_000, seed=2)
        ref = np.zeros((2, 512, 64), np.uint8)
        np_spread_update(ref, keys, elems)
        for threads in (1, 2, 8):
            regs = np.zeros((2, 512, 64), np.uint8)
            native.hs_spread_update(regs, keys, elems, threads)
            assert np.array_equal(ref, regs), f"threads={threads}"

    def test_saturated_planes_stay_saturated(self):
        """u8 edge: pre-saturated registers (e.g. merged-in extremes)
        must survive any further scatter-max and any merge untouched —
        max can never decrease, in any twin."""
        from flow_pipeline_tpu import native
        from flow_pipeline_tpu.ops.spread import spread_merge, spread_update

        keys, elems = _pairs(n=2000, seed=3)
        full = np.full((2, 64, 64), 255, np.uint8)
        for twin in ("numpy", "jnp", "native"):
            regs = full.copy()
            if twin == "numpy":
                np_spread_update(regs, keys, elems)
            elif twin == "jnp":
                import jax.numpy as jnp

                regs = np.asarray(
                    spread_update(jnp.asarray(regs), keys, elems),
                    dtype=np.uint8)
            elif native.spread_available():
                native.hs_spread_update(regs, keys, elems, 2)
            assert (regs == 255).all(), twin
        import jax.numpy as jnp
        merged = np.asarray(spread_merge(jnp.asarray(full), jnp.zeros_like(full)))
        assert (merged == 255).all()

    def test_chunking_invariance(self):
        """The max monoid: any split of the pair stream lands identical
        registers (the property the pipelines' pre-grouping leans on)."""
        keys, elems = _pairs(n=5000, seed=4)
        ref = np.zeros((2, 128, 64), np.uint8)
        np_spread_update(ref, keys, elems)
        for step in (1, 7, 999, 5000):
            regs = np.zeros((2, 128, 64), np.uint8)
            for s in range(0, len(keys), step):
                spread_apply_update(regs, keys[s:s + step],
                                    elems[s:s + step], threads=2)
            assert np.array_equal(ref, regs), f"step={step}"

    def test_rejects_elem_col_in_keys(self):
        with pytest.raises(ValueError, match="elem_col"):
            SpreadModel(SpreadConfig(key_cols=("src_addr",),
                                     elem_col="src_addr"))


# ---- pipelines -------------------------------------------------------------


class TestPipelineParity:
    """Every host pipeline folds spread bit-identically to the direct
    model update over the same batch (the citizenship gate)."""

    def _models(self):
        return {SUPERSPREADER_MODEL: superspreader_model(),
                SCAN_MODEL: scan_model()}

    def test_hostgroup_and_hostsketch_match_direct(self):
        batch = _zipf_batch()
        ref = self._models()
        for m in ref.values():
            m.update(batch)
        for cls, kw in ((HostGroupPipeline, {}),
                        (HostSketchPipeline,
                         dict(sketch_native="auto", fused="auto")),
                        (HostSketchPipeline,
                         dict(sketch_native="numpy", fused="off"))):
            models = self._models()
            p = cls(models, **kw)
            p.update(batch)
            if hasattr(p, "sync_states"):
                p.sync_states()
            for name in models:
                _assert_states_equal(_state_tuple(ref[name]),
                                     _state_tuple(models[name]),
                                     f"{cls.__name__}:{name}:")

    def test_top_rows_rank_by_decoded_spread(self):
        batch = _zipf_batch()
        m = superspreader_model()
        m.update(batch)
        top = m.model.top(32)
        assert top["valid"].all()
        spread = top["spread"]
        assert (np.diff(spread[top["valid"]]) <= 0).all()  # descending
        # the admission metric is an upper bound on the decoded estimate
        # only in expectation; but every reported spread must be the
        # register decode of that row's key, exactly
        keys = np.ascontiguousarray(top["src_addr"], np.uint32)
        again = np_spread_query(m.model.state.regs, keys)
        assert np.allclose(spread, again.astype(np.float32), rtol=1e-6)

    def test_spread_legs_rank_first(self):
        """The generator's harmonic fan-out legs are exactly what the
        detector must surface: leg sources (suffix 0xF000|rank) own the
        top of both detectors' tables."""
        batch = _zipf_batch(n=40_000)
        ss, sc = superspreader_model(), scan_model()
        ss.update(batch)
        sc.update(batch)
        for model, want_even in ((ss, True), (sc, False)):
            top = model.model.top(4)
            suf = np.asarray(top[model.config.key_cols[0]])[:, 3]
            assert ((suf & 0xF000) == 0xF000).all(), model
            ranks = suf & 0xFFF
            assert ((ranks % 2 == 0) == want_even).all(), model


# ---- mesh ------------------------------------------------------------------


class TestMeshExact:
    @pytest.mark.parametrize("n_members", [1, 2, 4])
    def test_merged_registers_bit_exact(self, n_members):
        batch = _zipf_batch()
        oracle = superspreader_model()
        oracle.update(batch)
        cfg = oracle.config

        ids = shard_ids(batch, n_members)
        payloads = []
        for i in range(n_members):
            member = superspreader_model()
            member.update(_sub_batch(batch, ids == i))
            blob = codec.encode(codec.capture_model(member.model))
            payloads.append(codec.decode(blob))
        merged = merge_ops.merge_spread(payloads, cfg)
        assert np.array_equal(merged["regs"], oracle.model.state.regs)
        # decoded rows identical too (the admission metric itself is
        # chunking-dependent and deliberately NOT compared)
        slot = 0
        rows = merge_ops.spread_top_rows(merged, cfg, 16, slot)
        want = spread_top_from(oracle.model.state, cfg, 16)
        for col in ("src_addr", "spread", "valid"):
            assert np.array_equal(rows[col], want[col]), col

    def test_member_restart_and_replay(self):
        """Churn leg: one member dies, restarts empty, replays its
        shard — the merged registers still equal the single worker's
        (idempotent max absorbs the replay)."""
        batch = _zipf_batch()
        oracle = superspreader_model()
        oracle.update(batch)
        ids = shard_ids(batch, 4)
        payloads = []
        for i in range(4):
            member = superspreader_model()
            member.update(_sub_batch(batch, ids == i))
            if i == 2:  # dies; a fresh member replays the same shard
                member = superspreader_model()
                member.update(_sub_batch(batch, ids == i))
                member.update(_sub_batch(batch, ids == i))  # partial re-read
            payloads.append(codec.capture_model(member.model))
        merged = merge_ops.merge_spread(payloads, oracle.config)
        assert np.array_equal(merged["regs"], oracle.model.state.regs)

    def test_mixed_family_fold_rejected(self):
        m = superspreader_model()
        m.update(_zipf_batch(n=2000))
        good = codec.capture_model(m.model)
        with pytest.raises(ValueError, match="mixed"):
            merge_ops.merge_spread([good, {"kind": "hh"}], m.config)


# ---- serve / gateway / checkpoint -----------------------------------------


def _fill_bus(batches=6, per=800, seed=91):
    bus = InProcessBus()
    bus.create_topic("flows", 1)
    gen = FlowGenerator(ZipfProfile(n_keys=500, spread_fraction=0.25),
                        seed=seed, t0=T0, rate=5.0)
    prod = Producer(bus, fixedlen=True)
    for _ in range(batches):
        prod.send_many(gen.batch(per).to_messages())
    return bus


def _spread_models():
    return {SUPERSPREADER_MODEL: superspreader_model(
        superspreader_config(capacity=128), k=16)}


def _get(port, path):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}").read())


class TestServeSpread:
    @pytest.fixture(scope="class")
    def served(self):
        worker = StreamWorker(
            Consumer(_fill_bus(), fixedlen=True), _spread_models(),
            [MemorySink()], WorkerConfig(snapshot_every=0, poll_max=512))
        pub = attach_worker(worker, refresh=0.0)
        while worker.run_once():
            pass
        with worker.lock:
            pub.publish(worker)
        serve = ServeServer(pub.store, port=0).start()
        yield worker, pub, serve
        serve.stop()

    def test_query_spread_key_decodes_live_registers(self, served):
        worker, pub, serve = served
        fam = pub.store.current.families[SUPERSPREADER_MODEL]
        assert fam.kind == "spread" and fam.regs is not None
        k = fam.rows["src_addr"][0]
        key = ",".join(str(int(x)) for x in np.atleast_1d(k))
        ans = _get(serve.port, f"/query/spread?model={SUPERSPREADER_MODEL}"
                               f"&key={key}")
        want = np_spread_query(fam.regs,
                               np.atleast_2d(np.asarray(k, np.uint32)))[0]
        assert np.isclose(ans["spread"], want, rtol=1e-9)
        assert np.isclose(ans["spread"], float(fam.rows["spread"][0]),
                          rtol=1e-6)

    def test_query_spread_topk_matches_rows(self, served):
        worker, pub, serve = served
        fam = pub.store.current.families[SUPERSPREADER_MODEL]
        ans = _get(serve.port, f"/query/spread?model={SUPERSPREADER_MODEL}"
                               f"&k=5")
        assert len(ans["rows"]) == 5
        assert [r["spread"] for r in ans["rows"]] == \
            [float(x) for x in fam.rows["spread"][:5]]

    def test_estimate_refuses_spread_family(self, served):
        worker, pub, serve = served
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(serve.port,
                 f"/query/estimate?model={SUPERSPREADER_MODEL}&key=1")
        assert ei.value.code == 400

    def test_gateway_delta_chain_reconstructs_spread(self, served):
        """regs ride the delta codec as dirty-column patches: full
        frame + delta == directly-encoded target, and /query/spread
        from the reconstructed state is BYTE-identical."""
        from flow_pipeline_tpu.gateway import (apply_delta, diff_states,
                                               snapshot_state,
                                               state_to_snapshot)
        from flow_pipeline_tpu.serve import SnapshotStore

        worker, pub, serve = served
        snap = pub.store.current
        st = snapshot_state(snap)
        # an older synthetic base: zeroed registers, same layout
        base = snapshot_state(snap)
        fname = SUPERSPREADER_MODEL
        base["families"][fname]["regs"] = np.zeros_like(
            base["families"][fname]["regs"])
        base["version"] = snap.version - 1
        delta = diff_states(base, st)
        fams = delta["families"][fname]
        assert ("regs" in fams or "regs_sparse" in fams
                or "regs_tiles" in fams)
        rebuilt = apply_delta(base, delta)
        assert np.array_equal(rebuilt["families"][fname]["regs"],
                              st["families"][fname]["regs"])
        mirror = SnapshotStore()
        mirror.publish_snapshot(state_to_snapshot(rebuilt))
        gw = ServeServer(mirror, port=0).start()
        try:
            path = (f"/query/spread?model={fname}&k=8")
            direct = urllib.request.urlopen(
                f"http://127.0.0.1:{serve.port}{path}").read()
            mirrored = urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}{path}").read()
            assert direct == mirrored
        finally:
            gw.stop()

    def test_checkpoint_round_trip(self, served, tmp_path):
        worker, pub, serve = served
        from flow_pipeline_tpu.engine.checkpoint import save_checkpoint

        path = str(tmp_path / "ckpt")
        with worker.lock:
            save_checkpoint(path, worker._state())
        fresh = StreamWorker(
            Consumer(_fill_bus(), fixedlen=True), _spread_models(),
            [MemorySink()], WorkerConfig(snapshot_every=0))
        assert fresh.restore(path)
        a = worker.models[SUPERSPREADER_MODEL].model.state
        b = fresh.models[SUPERSPREADER_MODEL].model.state
        assert np.array_equal(a.regs, b.regs)
        assert np.array_equal(a.table_keys, b.table_keys)
        assert np.array_equal(a.table_metric, b.table_metric)
        assert b.regs.dtype == np.uint8


# ---- sketchwatch spread audit ---------------------------------------------


class TestSpreadAudit:
    def test_full_mode_reports_small_median_error(self):
        models = {SUPERSPREADER_MODEL: superspreader_model()}
        p = HostGroupPipeline(models, audit="full")
        assert p.spread_audit is not None
        p.update(_zipf_batch(t0=T0))
        assert p.spread_audit._fams[SUPERSPREADER_MODEL].elems
        p.update(_zipf_batch(seed=8, t0=T0 + 600))  # closes the window
        rep = p.spread_audit.last_reports[SUPERSPREADER_MODEL]
        assert rep["sampled_keys"] > 0
        assert abs(rep["spread_abs_err"]["p50"]) < 0.25
        from flow_pipeline_tpu.obs.metrics import REGISTRY
        assert "sketch_spread_error_ratio" in REGISTRY.render()

    def test_audit_is_purely_observational(self):
        batch = _zipf_batch()
        on = {SUPERSPREADER_MODEL: superspreader_model()}
        off = {SUPERSPREADER_MODEL: superspreader_model()}
        HostGroupPipeline(on, audit="full").update(batch)
        HostGroupPipeline(off).update(batch)
        _assert_states_equal(_state_tuple(on[SUPERSPREADER_MODEL]),
                             _state_tuple(off[SUPERSPREADER_MODEL]))

    def test_paused_stops_cohort_refresh(self):
        models = {SUPERSPREADER_MODEL: superspreader_model()}
        p = HostGroupPipeline(models, audit="full")
        p.spread_audit.paused = True
        p.update(_zipf_batch())
        assert not p.spread_audit._fams[SUPERSPREADER_MODEL].elems


# ---- entropy companion -----------------------------------------------------


class TestEntropy:
    def test_uniform_is_one_collapse_is_zero(self):
        from flow_pipeline_tpu.models.ddos import rate_entropy

        h, active = rate_entropy(np.full(64, 10.0))
        assert active == 64 and np.isclose(h, 1.0)
        one = np.zeros(64)
        one[3] = 100.0
        h1, a1 = rate_entropy(one)
        assert a1 == 1 and h1 == 0.0
        h0, a0 = rate_entropy(np.zeros(64))
        assert a0 == 0 and h0 == 0.0

    def test_normalizes_by_full_bucket_count(self):
        """ln(M), not ln(active): a flood aimed at two dsts spreads
        evenly across two buckets — ln(active) would score that a
        perfect 1.0 instead of the collapse it is."""
        from flow_pipeline_tpu.models.ddos import rate_entropy

        two = np.zeros(64)
        two[1] = two[9] = 5.0
        h, active = rate_entropy(two)
        assert active == 2
        assert np.isclose(h, np.log(2) / np.log(64))
