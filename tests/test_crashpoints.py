"""flowtorn: crash-point model checking for every durable surface.

Each scenario here drives REAL production code (the coordinator
journal, the dead-letter spill, the history archive, the sketch
checkpoint) under ``fsutil.observed``, then hands the recorded op log
to ``utils/crashsim.explore`` — which materializes every legal crash
state (durable-effects-only, torn publishes, dropped directory
entries, torn/reordered unsynced writes) and runs the REAL recovery
code over each, asserting the docs/FAULT_TOLERANCE.md invariants:

- journal: every acked submission survives recovery bit-exact (or is
  subsumed by an acked compaction checkpoint);
- dead-letter: every acked spill replays to row equality;
- archive: every committed version reconstructs bit-equal, and a
  missing version is an honest HistoryGapError, never damaged data;
- checkpoint: an acked save restores exactly; mid-save crashes restore
  the complete predecessor.

The ``TestBarrierMutations`` half is the dynamic prong of the
``make lint-mutation`` durability gate: ``fsutil.suppressed(kind)``
deletes one barrier kind (fsync / dir-fsync / atomic replace) from the
protocol the way a bad refactor would, and every (surface, barrier)
pair must produce at least one crash-state invariant violation —
proof that each barrier in each surface is load-bearing, not
cargo-culted. The static prong (tests/test_flowlint.py) proves the
lint rule catches the same deletions in source form.
"""

import os

import numpy as np
import pytest

from flow_pipeline_tpu.engine.checkpoint import (checkpoint_exists,
                                                 load_checkpoint,
                                                 save_checkpoint)
from flow_pipeline_tpu.gateway.delta import encode_full
from flow_pipeline_tpu.history.archive import (ArchiveReader,
                                               ArchiveWriter,
                                               HistoryGapError)
from flow_pipeline_tpu.mesh.journal import (JOURNAL_FILE,
                                            CoordinatorJournal,
                                            replay_journal)
from flow_pipeline_tpu.sink.resilient import ResilientSink, replay_deadletter
from flow_pipeline_tpu.utils import crashsim, fsutil

T0 = 1_699_999_800


# ---- scenario: coordinator journal -----------------------------------------

_BLOBS = {"a": b"envelope-a" * 3, "b": b"envelope-b" * 5,
          "c": b"envelope-c" * 7}
_CHK_BLOB = b"compacted-coordinator-state"


def _run_journal(root: str, rec: fsutil.OpRecorder) -> None:
    """Append+ack three submissions with a compaction in the middle —
    the full journal lifecycle (init, group commit, atomic compact)."""
    with fsutil.observed(rec):
        j = CoordinatorJournal(os.path.join(root, "mesh"))
        j.append("sub", {"member": "a"}, _BLOBS["a"])
        j.sync()
        rec.mark("a")
        j.append("sub", {"member": "b"}, _BLOBS["b"])
        j.sync()
        rec.mark("b")
        j.compact({"epoch": 2}, _CHK_BLOB)
        rec.mark("chk")
        j.append("sub", {"member": "c"}, _BLOBS["c"])
        j.sync()
        rec.mark("c")
        j.close()


def _check_journal(croot: str, acked: list) -> None:
    recs = list(replay_journal(os.path.join(croot, "mesh", JOURNAL_FILE)))
    chk = next((blob for kind, _m, blob in recs if kind == "chk"), None)
    subs = {m["member"]: blob for kind, m, blob in recs if kind == "sub"}
    for label in acked:
        if label == "chk":
            assert chk is not None, "acked compaction checkpoint lost"
            assert chk == _CHK_BLOB, "checkpoint blob not bit-exact"
        elif label in ("a", "b") and chk is not None:
            continue  # folded into the (also durable) checkpoint
        else:
            assert label in subs, f"acked submission {label!r} lost"
            assert subs[label] == _BLOBS[label], \
                f"submission {label!r} not bit-exact"


# ---- scenario: dead-letter spill -------------------------------------------

_BATCHES = {
    "batch1": [{"src_addr": "10.0.0.1", "bytes": 100, "flows": 2}],
    "batch2": [{"src_addr": "10.0.0.2", "bytes": 7, "flows": 1},
               {"src_addr": "10.0.0.3", "bytes": 9, "flows": 4}],
}


class _DownSink:
    def write(self, table, rows):
        raise OSError("sink is down")


class _CollectSink:
    def __init__(self):
        self.rows = set()

    def write(self, table, records):
        for r in records:
            self.rows.add((table, tuple(sorted(r.items()))))


def _run_dlq(root: str, rec: fsutil.OpRecorder) -> None:
    sink = ResilientSink(_DownSink(), retries=2, backoff=0.0, jitter=0.0,
                         deadletter_dir=os.path.join(root, "sink"),
                         sleep=lambda _s: None)
    with fsutil.observed(rec):
        for label, rows in _BATCHES.items():
            sink.write("flows", rows)  # exhausts retries, spills
            rec.mark(label)


def _check_dlq(croot: str, acked: list) -> None:
    col = _CollectSink()
    # a torn acked spill raises here — that IS the invariant violation
    replay_deadletter(os.path.join(croot, "sink"), [col], delete=False)
    for label in acked:
        for r in _BATCHES[label]:
            key = ("flows", tuple(sorted(r.items())))
            assert key in col.rows, f"acked spill {label!r} lost {r}"


# ---- scenario: history archive ---------------------------------------------


def _mk_state(version: int, *, bump: int = 0) -> dict:
    """A compact canonical gateway state (one hh family, one range
    table) — the delta-codec shape the archive persists."""
    rng = np.random.default_rng(7)
    cms = rng.integers(0, 1000, size=(2, 2, 8)).astype(np.uint64)
    if bump:
        cms[0, 1, bump % 8] += np.uint64(bump)
    return {
        "version": int(version), "created": 100.0 + version,
        "watermark": float(T0 + 300 * version),
        "flows_seen": 10 * version, "source": "worker",
        "families": {
            "hh": {"kind": "hh", "window_start": T0, "depth": 4,
                   "key_lanes": 2, "value_cols": ["bytes"],
                   "rows": {
                       "src_addr": np.arange(4, dtype=np.uint32)
                       + np.uint32(bump),
                       "bytes": np.asarray([9.0, 5.0, 3.0, 1.0],
                                           np.float32),
                       "valid": np.asarray([True, True, True, False]),
                   },
                   "cms": cms, "regs": None},
        },
        "ranges": {"flows_5m": [
            [T0, {"timeslot": np.asarray([T0, T0], np.int64),
                  "bytes": np.asarray([1, 2 + bump], np.uint64)}],
        ]},
        "audit": {"hh": {"cms_err": 0.0, "windows": version}},
    }


_STATES = {v: _mk_state(v, bump=v - 1) for v in (1, 2, 3, 4, 5)}


def _run_archive(root: str, rec: fsutil.OpRecorder) -> None:
    """Five versions at keyframe_every=2: two rotations, commits that
    cover records in BOTH the rotated-away and the live segment."""
    with fsutil.observed(rec):
        w = ArchiveWriter(os.path.join(root, "hist"), keyframe_every=2)
        prev = None
        committed = []
        for v in sorted(_STATES):
            w.record(prev, _STATES[v])
            prev = _STATES[v]
            committed.append(v)
            if v % 2 == 0 or v == max(_STATES):
                w.commit()
                for c in committed:
                    rec.mark(f"v{c}")
                committed = []
        w.close()


def _check_archive(croot: str, acked: list) -> None:
    rd = ArchiveReader(os.path.join(croot, "hist"))
    versions = set(rd.versions())
    for label in acked:
        v = int(label[1:])
        assert v in versions, f"archived v{v} lost"
        state = rd.reconstruct(v)
        assert encode_full(state) == encode_full(_STATES[v]), \
            f"v{v} did not reconstruct bit-equal"
    # honesty: everything listed reconstructs, everything else is a
    # loud gap — never a damaged snapshot
    for v in versions:
        rd.reconstruct(v)
    with pytest.raises(HistoryGapError):
        rd.reconstruct(max(versions, default=0) + 1)


# ---- scenario: sketch checkpoint -------------------------------------------

_CKPT_1 = {"step": 1, "hh": np.arange(6, dtype=np.uint64)}
_CKPT_2 = {"step": 2, "hh": np.arange(6, dtype=np.uint64) * 3}


def _run_checkpoint(root: str, rec: fsutil.OpRecorder) -> None:
    path = os.path.join(root, "ckpt", "snap")
    with fsutil.observed(rec):
        save_checkpoint(path, _CKPT_1)
        rec.mark("s1")
        save_checkpoint(path, _CKPT_2)  # exercises the .old dance
        rec.mark("s2")


def _ckpt_equal(got: dict, want: dict) -> bool:
    return got["step"] == want["step"] and \
        np.array_equal(got["hh"], want["hh"])


def _check_checkpoint(croot: str, acked: list) -> None:
    path = os.path.join(croot, "ckpt", "snap")
    if not acked:
        if not checkpoint_exists(path):
            return  # crashed before anything was published: fine
        got = load_checkpoint(path)  # must load completely or raise
        assert _ckpt_equal(got, _CKPT_1) or _ckpt_equal(got, _CKPT_2), \
            "checkpoint on disk matches neither saved state"
        return
    got = load_checkpoint(path)
    if "s2" in acked:
        assert _ckpt_equal(got, _CKPT_2), \
            "acked checkpoint s2 did not restore"
    else:
        # s1 acked, s2 mid-save: the complete predecessor or the
        # complete successor — never a torn mix
        assert _ckpt_equal(got, _CKPT_1) or _ckpt_equal(got, _CKPT_2), \
            "acked checkpoint restored a torn state"


_SCENARIOS = {
    "journal": (_run_journal, _check_journal),
    "deadletter": (_run_dlq, _check_dlq),
    "archive": (_run_archive, _check_archive),
    "checkpoint": (_run_checkpoint, _check_checkpoint),
}


def _explore(tmp_path, surface: str, **kw) -> crashsim.CrashReport:
    run, check = _SCENARIOS[surface]
    root = str(tmp_path)
    rec = fsutil.OpRecorder()
    run(root, rec)
    assert rec.ops, "scenario recorded no durable ops"
    return crashsim.explore(rec, root, check, **kw)


# ---- the gate: every crash window of every surface -------------------------


class TestCrashPoints:

    @pytest.mark.parametrize("surface", sorted(_SCENARIOS))
    def test_every_crash_state_recovers(self, tmp_path, surface):
        report = _explore(tmp_path, surface)
        assert report.crash_points > 10, report.render()
        assert report.states_explored > 10, report.render()
        assert report.ok, report.render()

    def test_final_state_is_complete(self, tmp_path):
        """The no-crash run itself satisfies every invariant (sanity:
        the checkers are not vacuous)."""
        for surface in sorted(_SCENARIOS):
            run, check = _SCENARIOS[surface]
            root = str(tmp_path / surface)
            rec = fsutil.OpRecorder()
            run(root, rec)
            check(root, [m[1] for m in rec.ops if m[0] == "mark"])


# ---- the dynamic mutation gate ---------------------------------------------


class TestBarrierMutations:
    """Delete one barrier kind from one surface's protocol; the model
    checker must find a crash state that violates an invariant. A
    mutation that nothing catches means the barrier was decorative."""

    CASES = [
        ("journal", "fsync"), ("journal", "fsync_dir"),
        ("journal", "replace"),
        ("deadletter", "fsync"), ("deadletter", "fsync_dir"),
        ("deadletter", "replace"),
        ("checkpoint", "fsync"), ("checkpoint", "fsync_dir"),
        ("checkpoint", "replace"),
        # the archive publishes by append+rotate, never by replace
        ("archive", "fsync"), ("archive", "fsync_dir"),
    ]

    @pytest.mark.parametrize("surface,barrier",
                             CASES, ids=[f"{s}-{b}" for s, b in CASES])
    def test_dropped_barrier_is_caught(self, tmp_path, surface, barrier):
        run, check = _SCENARIOS[surface]
        root = str(tmp_path)
        rec = fsutil.OpRecorder()
        with fsutil.suppressed(barrier):
            run(root, rec)
        report = crashsim.explore(rec, root, check, fail_fast=True)
        assert not report.ok, (
            f"deleting every {barrier!r} barrier from the {surface} "
            f"protocol produced no crash-state violation — the model "
            f"checker lost its teeth\n{report.render()}")

    def test_unknown_barrier_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown suppressible"):
            with fsutil.suppressed("flush"):
                pass


# ---- satellite: checkpoint crash-mid-save specifics ------------------------


class TestCheckpointMidSave:

    def test_crash_between_renames_restores_predecessor(self, tmp_path):
        """Simulate the exact mid-dance crash: the old checkpoint moved
        to .old, the new one never renamed in. Load must fall back to
        the complete predecessor."""
        path = str(tmp_path / "snap")
        save_checkpoint(path, _CKPT_1)
        os.rename(path, path + ".old")  # crash window between renames
        assert checkpoint_exists(path)
        assert _ckpt_equal(load_checkpoint(path), _CKPT_1)
        # and the next save self-heals the stale .old
        save_checkpoint(path, _CKPT_2)
        assert not os.path.isdir(path + ".old")
        assert _ckpt_equal(load_checkpoint(path), _CKPT_2)

    def test_torn_payload_rejects_loudly(self, tmp_path):
        """A damaged arrays.npz must raise, never silently decode."""
        path = str(tmp_path / "snap")
        save_checkpoint(path, _CKPT_1)
        with open(os.path.join(path, "arrays.npz"), "wb") as f:
            f.write(b"\0\0\0\0")
        with pytest.raises(Exception):
            load_checkpoint(path)

    def test_failed_save_keeps_previous(self, tmp_path, monkeypatch):
        path = str(tmp_path / "snap")
        save_checkpoint(path, _CKPT_1)
        real = fsutil.write_bytes_durable

        def boom(p, data):
            if p.endswith("meta.json"):
                raise OSError("disk full")
            real(p, data)

        monkeypatch.setattr(fsutil, "write_bytes_durable", boom)
        with pytest.raises(OSError, match="disk full"):
            save_checkpoint(path, _CKPT_2)
        monkeypatch.setattr(fsutil, "write_bytes_durable", real)
        assert _ckpt_equal(load_checkpoint(path), _CKPT_1)
        # no staging litter left behind
        litter = [n for n in os.listdir(tmp_path)
                  if n.startswith(".ckpt-")]
        assert litter == []
