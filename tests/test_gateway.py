"""flowgate tests: the replicated, delta-fed serve gateway (gateway/).

The contracts pinned here, per docs/ARCHITECTURE.md "flowgate":

- the delta codec reconstructs snapshots BIT-EXACTLY: a full frame
  followed by any chain of deltas equals the directly-encoded target
  state, array for array, dtype for dtype (uint64 extremes included);
- every ``/query/{topk,estimate,range,audit}`` answer served through a
  gateway is byte-identical to the direct snapshot path's at the same
  version — worker AND mesh publishers, table AND invertible sketches;
- damage never guesses: a torn frame, CRC mismatch, or chain gap
  forces a FULL resync, and the serving store keeps its last good
  snapshot (versions monotone) while the mirror recovers;
- replication: killing one of K gateway replicas is invisible to a
  :class:`GatewayClient` (zero 5xx, zero surfaced errors, versions
  monotone through the failover), and killing a mesh WORKER under
  gateway read load stays invisible too;
- the hot query set is pre-rendered at snapshot-landing time (the p99
  path is a cache hit before the first reader asks).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from flow_pipeline_tpu.engine import (StreamWorker, WindowedHeavyHitter,
                                      WorkerConfig)
from flow_pipeline_tpu.gateway import (DeltaError, DeltaGapError,
                                       GatewayClient, HashRing,
                                       SnapshotFeed, SnapshotGateway,
                                       apply_delta, decode_frames,
                                       diff_states, encode_delta,
                                       encode_full, snapshot_state,
                                       state_to_snapshot)
from flow_pipeline_tpu.gateway import delta as delta_mod
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.models import (HeavyHitterConfig, WindowAggConfig,
                                      WindowAggregator)
from flow_pipeline_tpu.serve import ServeServer, SnapshotStore, attach_worker
from flow_pipeline_tpu.sink import MemorySink
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer
from flow_pipeline_tpu.utils.faults import FAULTS

T0 = 1_699_999_800  # window-aligned stream start


@pytest.fixture(autouse=True)
def _faults_disarmed():
    yield
    FAULTS.configure(None)


def _fill_bus(batches=8, per=500, rate=5.0, seed=91, partitions=1):
    bus = InProcessBus()
    bus.create_topic("flows", partitions)
    gen = FlowGenerator(ZipfProfile(n_keys=100, alpha=1.3), seed=seed,
                        t0=T0, rate=rate)
    prod = Producer(bus, fixedlen=True)
    for _ in range(batches):
        prod.send_many(gen.batch(per).to_messages())
    return bus


def _models(hh_sketch="table"):
    return {
        "flows_5m": WindowAggregator(WindowAggConfig(batch_size=512)),
        "top_talkers": WindowedHeavyHitter(
            HeavyHitterConfig(batch_size=512, width=1 << 12, capacity=64,
                              hh_sketch=hh_sketch),
            k=10),
    }


def _get_raw(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10).read()


def _get(port, path):
    return json.loads(_get_raw(port, path))


def _run_worker(hh_sketch="table", **worker_kw):
    """Quiesced worker + per-window-close publishes; returns (worker,
    publisher) with the final snapshot at the exact consumed point."""
    worker = StreamWorker(
        Consumer(_fill_bus(), fixedlen=True), _models(hh_sketch),
        [MemorySink()],
        WorkerConfig(snapshot_every=0, poll_max=512, **worker_kw))
    pub = attach_worker(worker, refresh=0.0)
    while worker.run_once():
        with worker.lock:
            pub.publish(worker)
    with worker.lock:
        pub.publish(worker)
    # the bus is drained and later tests only read worker state, so stop
    # the pipeline threads here: leaked daemon pollers keep hitting the
    # bus.poll fault seam and pollute FAULTS counters suite-wide
    if worker.executor is not None:
        worker.executor.stop()
    if worker.flusher is not None:
        worker.flusher.stop()
    stop_feed = getattr(worker.consumer, "stop", None)
    if stop_feed is not None:
        stop_feed()
    return worker, pub


# ---- delta codec (unit, synthetic states) ----------------------------------


def _mk_state(version, *, width=8, bump=0, extremes=False):
    """Synthetic canonical state: one hh family (+u64 CMS planes), one
    dense family (no CMS), one range table, an audit blob."""
    rng = np.random.default_rng(7)  # same base every version: deltas
    cms = rng.integers(0, 1000, size=(3, 2, width)).astype(np.uint64)
    if extremes:
        cms[0, 0, 0] = np.uint64(2**64 - 1)
        cms[1, 0, 1] = np.uint64(2**53 + 1)
        cms[2, 1, width - 1] = np.uint64(0)
    if bump:
        cms[0, 1, bump % width] += np.uint64(bump)
    rows = {
        "src_addr": np.arange(4, dtype=np.uint32) + np.uint32(bump),
        "bytes": np.asarray([9.0, 5.0, 3.0, 1.0], np.float32),
        "valid": np.asarray([True, True, True, False]),
    }
    return {
        "version": int(version), "created": 100.0 + version,
        "watermark": float(T0 + 300 * version), "flows_seen": 10 * version,
        "source": "worker",
        "families": {
            "hh": {"kind": "hh", "window_start": T0, "depth": 4,
                   "key_lanes": 2, "value_cols": ["bytes"],
                   "rows": rows, "cms": cms},
            "dense": {"kind": "dense", "window_start": T0, "depth": 4,
                      "key_lanes": 1, "value_cols": [],
                      "rows": {"port": np.arange(4, dtype=np.uint32)},
                      "cms": None},
        },
        "ranges": {"flows_5m": [
            [T0, {"timeslot": np.asarray([T0, T0], np.int64),
                  "bytes": np.asarray([1, 2], np.uint64)}],
            [T0 + 300 * max(1, bump),
             {"timeslot": np.asarray([T0 + 300], np.int64),
              "bytes": np.asarray([3 + bump], np.uint64)}],
        ]},
        "audit": {"hh": {"cms_err": 0.0, "windows": version}},
    }


def _assert_states_equal(a, b):
    assert a["version"] == b["version"]
    assert a["watermark"] == b["watermark"]
    assert a["flows_seen"] == b["flows_seen"]
    assert set(a["families"]) == set(b["families"])
    for name, f in a["families"].items():
        g = b["families"][name]
        for k in ("kind", "window_start", "depth", "key_lanes"):
            assert f[k] == g[k], (name, k)
        assert list(f["value_cols"]) == list(g["value_cols"])
        assert set(f["rows"]) == set(g["rows"])
        for c in f["rows"]:
            x, y = np.asarray(f["rows"][c]), np.asarray(g["rows"][c])
            assert x.dtype == y.dtype and np.array_equal(x, y), (name, c)
        if f["cms"] is None:
            assert g["cms"] is None
        else:
            assert g["cms"] is not None
            assert f["cms"].dtype == g["cms"].dtype
            assert np.array_equal(f["cms"], g["cms"])
    assert set(a["ranges"]) == set(b["ranges"])
    for t, slots in a["ranges"].items():
        gslots = b["ranges"][t]
        assert [int(s) for s, _ in slots] == [int(s) for s, _ in gslots]
        for (_, rows), (_, grows) in zip(slots, gslots):
            assert set(rows) == set(grows)
            for c in rows:
                assert np.array_equal(np.asarray(rows[c]),
                                      np.asarray(grows[c]))
    assert a["audit"] == b["audit"]


class TestDeltaCodec:
    def test_full_round_trip_bit_exact(self):
        st = _mk_state(3, extremes=True)
        tree = next(decode_frames(encode_full(st)))
        assert tree["t"] == "full"
        _assert_states_equal(st, tree["state"])

    def test_delta_chain_reconstructs_bit_exact(self):
        states = [_mk_state(v, bump=v) for v in range(1, 6)]
        cur = next(decode_frames(encode_full(states[0])))["state"]
        for i in range(1, len(states)):
            tree = next(decode_frames(encode_delta(states[i - 1],
                                                   states[i])))
            assert tree["t"] == "delta"
            cur = apply_delta(cur, tree)
            _assert_states_equal(states[i], cur)

    def test_u64_extreme_tiles_patch_exactly(self):
        a = _mk_state(1)
        b = _mk_state(2, bump=0, extremes=True)
        b["version"] = 2
        d = diff_states(a, b)
        got = apply_delta(a, d)
        _assert_states_equal(b, got)
        assert int(got["families"]["hh"]["cms"][0, 0, 0]) == 2**64 - 1
        assert int(got["families"]["hh"]["cms"][1, 0, 1]) == 2**53 + 1

    def test_unchanged_cms_travels_as_nothing(self):
        a = _mk_state(1)
        b = _mk_state(2)  # same arrays, new metadata
        d = diff_states(a, b)
        hh = d["families"]["hh"]
        assert "cms" not in hh and "cms_tiles" not in hh
        assert "rows" not in hh  # ranked rows identical too
        got = apply_delta(a, d)
        # carried forward BY REFERENCE, not copied
        assert got["families"]["hh"]["cms"] is a["families"]["hh"]["cms"]
        _assert_states_equal(b, got)

    def test_sparse_rows_ship_only_touched_columns(self):
        a = _mk_state(1, width=512)
        b = _mk_state(2, width=512)
        b["families"]["hh"]["cms"] = a["families"]["hh"]["cms"].copy()
        b["families"]["hh"]["cms"][0, 0, 5] += np.uint64(1)
        b["families"]["hh"]["cms"][2, 0, 300] = np.uint64(2**64 - 1)
        hh = diff_states(a, b)["families"]["hh"]
        assert "cms_tiles" not in hh  # nothing dense enough for slabs
        sparse = hh["cms_sparse"]
        assert len(sparse) == 1  # one dirty depth row
        d, cols, vals = sparse[0]
        assert (d, list(cols)) == (0, [5, 300])
        assert vals.shape == (3, 2) and vals.dtype == np.uint64
        _assert_states_equal(b, apply_delta(a, diff_states(a, b)))

    def test_dense_rows_fall_back_to_tiles(self):
        a = _mk_state(1, width=512)
        b = _mk_state(2, width=512)
        cms = a["families"]["hh"]["cms"].copy()
        cms[:, 1, :] += np.uint64(1)  # whole depth row dirty
        b["families"]["hh"]["cms"] = cms
        hh = diff_states(a, b)["families"]["hh"]
        assert "cms_sparse" not in hh
        assert {int(d) for d, _, _ in hh["cms_tiles"]} == {1}
        _assert_states_equal(b, apply_delta(a, diff_states(a, b)))

    def test_gap_rejected(self):
        a, b, c = (_mk_state(v, bump=v) for v in (1, 2, 3))
        d_bc = diff_states(b, c)
        with pytest.raises(DeltaGapError):
            apply_delta(a, d_bc)

    def test_reordered_chain_rejected(self):
        a, b, c = (_mk_state(v, bump=v) for v in (1, 2, 3))
        d_ab, d_bc = diff_states(a, b), diff_states(b, c)
        mid = apply_delta(a, d_ab)
        assert mid["version"] == 2
        with pytest.raises(DeltaGapError):
            apply_delta(apply_delta(a, d_ab), d_ab)  # replayed link
        with pytest.raises(DeltaGapError):
            apply_delta(a, d_bc)  # skipped link

    def test_torn_and_corrupt_frames_rejected(self):
        frame = encode_full(_mk_state(1))
        with pytest.raises(DeltaError):
            list(decode_frames(frame[:-3]))  # torn body
        bad = bytearray(frame)
        bad[-1] ^= 0xFF
        with pytest.raises(DeltaError):
            list(decode_frames(bytes(bad)))  # CRC mismatch
        with pytest.raises(DeltaError):
            list(decode_frames(b"NOPE" + frame))  # bad magic

    def test_concatenated_frames_decode_in_order(self):
        a, b = _mk_state(1, bump=1), _mk_state(2, bump=2)
        data = encode_full(a) + encode_delta(a, b)
        kinds = [t["t"] for t in decode_frames(data)]
        assert kinds == ["full", "delta"]


try:  # property test where hypothesis exists (repo convention)
    from hypothesis import given, settings
    from hypothesis import strategies as st

    @settings(max_examples=25, deadline=None)
    @given(st.lists(st.integers(0, 2**64 - 1), min_size=8, max_size=8),
           st.lists(st.integers(0, 2**64 - 1), min_size=8, max_size=8))
    def test_delta_property_u64_planes(base_words, new_words):
        """Any pair of u64 plane states diff+apply to the target
        exactly — wraparound extremes included."""
        a, b = _mk_state(1), _mk_state(2)
        a["families"]["hh"]["cms"] = np.asarray(
            base_words, np.uint64).reshape(1, 1, 8)
        b["families"]["hh"]["cms"] = np.asarray(
            new_words, np.uint64).reshape(1, 1, 8)
        got = apply_delta(a, diff_states(a, b))
        assert np.array_equal(got["families"]["hh"]["cms"],
                              b["families"]["hh"]["cms"])
except ImportError:  # pragma: no cover
    pass


# ---- feed ------------------------------------------------------------------


class TestSnapshotFeed:
    def _store_at(self, versions):
        store = SnapshotStore()
        for v in versions:
            store.publish_snapshot(state_to_snapshot(_mk_state(v, bump=v)))
        return store

    def test_none_delta_full_decisions(self):
        store = self._store_at([1])
        feed = SnapshotFeed(store)
        kind, cur, frames = feed.frame_since(0)
        assert (kind, cur) == ("full", 1)
        assert next(decode_frames(frames))["t"] == "full"
        kind, cur, _ = feed.frame_since(1)
        assert (kind, cur) == ("none", 1)
        store.publish_snapshot(state_to_snapshot(_mk_state(2, bump=2)))
        kind, cur, frames = feed.frame_since(1)
        assert (kind, cur) == ("delta", 2)
        assert next(decode_frames(frames))["t"] == "delta"
        # an unknown since (never observed) -> full
        kind, _, _ = feed.frame_since(99)
        assert kind == "full"

    def test_chain_spans_multiple_observed_versions(self):
        store = self._store_at([1])
        feed = SnapshotFeed(store)
        feed.frame_since(0)  # observe v1
        for v in (2, 3, 4):
            store.publish_snapshot(
                state_to_snapshot(_mk_state(v, bump=v)))
            feed.frame_since(v)  # observe each
        kind, cur, frames = feed.frame_since(1)
        assert (kind, cur) == ("delta", 4)
        trees = list(decode_frames(frames))
        assert [t["from"] for t in trees] == [1, 2, 3]
        assert [t["to"] for t in trees] == [2, 3, 4]

    def test_history_eviction_forces_full(self):
        store = self._store_at([1])
        feed = SnapshotFeed(store, history=2)
        feed.frame_since(0)
        for v in (2, 3, 4, 5):
            store.publish_snapshot(
                state_to_snapshot(_mk_state(v, bump=v)))
            feed.frame_since(v)
        kind, _, _ = feed.frame_since(1)  # evicted link
        assert kind == "full"
        kind, _, _ = feed.frame_since(3)  # still in history
        assert kind == "delta"

    def test_byte_budget_evicts_oldest_links(self):
        """Count-only retention holds ~FEED_HISTORY full-snapshot-sized
        frames when every CMS tile is dirty (delta ~= full — bench.py
        records the ratio): the byte budget evicts the oldest links
        first, widening the full-resync window instead of growing
        resident memory (the r17 journal lesson, on RAM)."""
        store = self._store_at([1])
        feed = SnapshotFeed(store, history_bytes=0)  # hold no deltas
        feed.frame_since(0)
        store.publish_snapshot(state_to_snapshot(_mk_state(2, bump=2)))
        kind, cur, _ = feed.frame_since(1)
        assert (kind, cur) == ("full", 2)  # the only link was evicted
        assert not feed._deltas and feed._delta_bytes_held == 0
        # the held-bytes ledger stays consistent through the COUNT cap
        store2 = self._store_at([1])
        feed2 = SnapshotFeed(store2, history=2)
        feed2.frame_since(0)
        for v in (2, 3, 4, 5):
            store2.publish_snapshot(
                state_to_snapshot(_mk_state(v, bump=v)))
            feed2.frame_since(v)
        assert len(feed2._deltas) == 2
        assert feed2._delta_bytes_held == sum(
            len(f) for _, _, f in feed2._deltas)

    def test_stats_ledger_counts_both_codings(self):
        store = self._store_at([1])
        feed = SnapshotFeed(store)
        feed.frame_since(0)
        store.publish_snapshot(state_to_snapshot(_mk_state(2, bump=2)))
        feed.frame_since(1)
        s = feed.stats()
        assert s["publishes"] == 2 and s["deltas"] == 1
        assert 0 < s["delta_bytes"] < s["full_bytes"]


@pytest.mark.slow  # worker + serve churn; gated by `make gateway-parity`
class TestConditionalPolls:
    """r19: ETag-conditional subscription polls (the r18 named
    follow-on). A subscriber that is already current revalidates with
    If-None-Match and the "none" answer costs HEADERS, NOT BYTES —
    while a stale subscriber's etag can never mask a delta/full ship
    (the etag encodes the CURRENT feed version, so it only matches a
    poll whose since is already current)."""

    def test_304_costs_headers_not_bytes(self):
        _, pub = _run_worker()
        serve = ServeServer(pub.store, port=0).start()
        try:
            cur = pub.store.current.version
            # unconditional "none" poll: a real frame body every time
            uncond = _get_raw(serve.port, f"/sub/snapshot?since={cur}")
            assert len(uncond) > 0
            # conditional: 304 with a ZERO-byte body — that frame's
            # bytes are exactly what the etag saves per quiet poll
            req = urllib.request.Request(
                f"http://127.0.0.1:{serve.port}/sub/snapshot"
                f"?since={cur}",
                headers={"If-None-Match": f'"sub-v{cur}"'})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 304
            assert ei.value.read() == b""
            assert ei.value.headers["ETag"] == f'"sub-v{cur}"'
            # a STALE subscriber sending its own (old) etag still gets
            # the full/delta body — the ship cannot be masked
            req = urllib.request.Request(
                f"http://127.0.0.1:{serve.port}/sub/snapshot?since=0",
                headers={"If-None-Match": '"sub-v0"'})
            resp = urllib.request.urlopen(req, timeout=10)
            assert resp.status == 200 and len(resp.read()) > 0
        finally:
            serve.stop()

    def test_gateway_quiet_polls_ship_zero_bytes(self):
        """The subscriber side: _Upstream.fetch sends the conditional
        header, maps 304 to zero frames, and the mirror loop reads it
        as a clean "none" — byte ledger checked at the fetch seam."""
        worker, pub = _run_worker()
        serve = ServeServer(pub.store, port=0).start()
        gw = SnapshotGateway([f"127.0.0.1:{serve.port}"], poll=60)
        try:
            assert gw.sync_once() == "full"
            up = gw.upstreams[0]
            # quiet upstream: the conditional poll costs zero body bytes
            assert up.fetch(up.version) == b""
            assert gw.sync_once() == "none"
            # a publish immediately lands as a delta — never masked
            with worker.lock:
                pub.publish(worker)
            assert gw.sync_once() == "delta"
            assert gw.store.current.version == pub.store.current.version
        finally:
            serve.stop()


# ---- the bit-exactness gate ------------------------------------------------


PARITY_PATHS = (
    "/query/topk", "/query/topk?k=0", "/query/topk?k=1",
    "/query/topk?k=5", "/query/topk?model=top_talkers&k=10",
    "/query/topk?model=flows_5m&k=3",
    "/query/range", "/query/range?model=flows_5m",
    "/query/audit",
)


def _assert_gateway_parity(direct_port, gw_port, store):
    """Every query answer byte-identical; /query/version identical
    modulo age_seconds (live by definition)."""
    paths = list(PARITY_PATHS)
    snap = store.current
    fam = snap.families["top_talkers"]
    for seedlane in (7, 2**32 - 1):
        key = ",".join(str(seedlane) for _ in range(fam.key_lanes))
        paths.append(f"/query/estimate?model=top_talkers&key={key}")
    slots = [s for s, _ in snap.ranges.get("flows_5m", ())]
    if slots:
        paths.append(f"/query/range?from={slots[0]}&to={slots[-1] + 1}")
        paths.append(f"/query/range?from={slots[-1]}")
    for path in paths:
        try:
            a = _get_raw(direct_port, path)
        except urllib.error.HTTPError as e:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_raw(gw_port, path)
            assert ei.value.code == e.code, path
            continue
        b = _get_raw(gw_port, path)
        assert a == b, path
    v1, v2 = _get(direct_port, "/query/version"), \
        _get(gw_port, "/query/version")
    v1.pop("age_seconds"), v2.pop("age_seconds")
    assert v1 == v2


class TestGatewayParity:
    """Acceptance: every /query/* answer through a gateway equals the
    direct snapshot-path answer at the same version."""

    @pytest.fixture(scope="class", params=["table", "invertible"])
    def served(self, request):
        kw = {}
        if request.param == "invertible":
            kw = dict(sketch_backend="host", host_assist="on")
        worker, pub = _run_worker(hh_sketch=request.param, **kw)
        serve = ServeServer(pub.store, port=0).start()
        yield worker, pub, serve
        serve.stop()

    def test_http_mirror_is_bit_exact(self, served):
        _, pub, serve = served
        gw = SnapshotGateway([f"127.0.0.1:{serve.port}"], poll=60)
        gws = ServeServer(gw.store, port=0).start()
        gw.serve_on(gws)
        try:
            assert gw.sync_once() == "full"
            assert gw.store.current.version == pub.store.current.version
            _assert_gateway_parity(serve.port, gws.port, pub.store)
        finally:
            gws.stop()

    def test_delta_fed_mirror_is_bit_exact(self, served):
        """The same gate with the mirror built INCREMENTALLY: full
        once, then every subsequent publish applied as a delta."""
        worker, pub, serve = served
        gw = SnapshotGateway([pub.store], poll=60)
        gws = ServeServer(gw.store, port=0).start()
        gw.serve_on(gws)
        try:
            assert gw.sync_once() == "full"
            kinds = []
            for _ in range(3):
                with worker.lock:
                    pub.publish(worker)
                kinds.append(gw.sync_once())
            assert set(kinds) == {"delta"}
            assert gw.store.current.version == pub.store.current.version
            _assert_gateway_parity(serve.port, gws.port, pub.store)
        finally:
            gws.stop()

    def test_prerendered_hot_set_lands_with_the_snapshot(self, served):
        _, pub, serve = served
        gw = SnapshotGateway([pub.store], poll=60)
        gws = ServeServer(gw.store, port=0).start()
        gw.serve_on(gws)
        try:
            gw.sync_once()
            # the hot targets are in the raw-target alias cache BEFORE
            # any reader asked
            assert "/query/topk" in gws._alias
            assert "/query/topk?model=top_talkers" in gws._alias
            assert gw._m["prerendered"].value() >= 2
            # and the pre-rendered body is the served body
            etag, body = gws._alias["/query/topk"]
            assert _get_raw(gws.port, "/query/topk") == body
        finally:
            gws.stop()


@pytest.mark.slow
class TestMeshGatewayParity:
    """Marked slow (an 8k-flow 2-member mesh ingest): runs in
    `make gateway-parity` / CI; the worker-publisher parity class
    below carries the tier-1 bit-exactness gate."""

    def test_merged_view_through_gateway_is_bit_exact(self):
        """Acceptance, mesh leg: a gateway mirroring the COORDINATOR's
        merged snapshot stream answers every endpoint byte-identical
        to the coordinator's own serve surface."""
        from flow_pipeline_tpu.mesh import InProcessMesh, produce_sharded
        from flow_pipeline_tpu.serve import attach_mesh

        def mesh_models():
            return {
                "flows_5m": WindowAggregator(
                    WindowAggConfig(batch_size=512)),
                "top_talkers": WindowedHeavyHitter(
                    HeavyHitterConfig(
                        key_cols=("src_addr", "dst_addr", "src_port",
                                  "dst_port", "proto"),
                        batch_size=512, width=1 << 12, capacity=128),
                    k=10),
            }

        bus = InProcessBus()
        bus.create_topic("flows", 4)
        gen = FlowGenerator(ZipfProfile(n_keys=200, alpha=1.3), seed=7,
                            t0=1_700_000_000, rate=40.0)
        done = 0
        while done < 8000:
            done += produce_sharded(bus, "flows", gen.batch(2048), 4)
        mesh = InProcessMesh(
            bus, "flows", 2, model_factory=mesh_models,
            config=WorkerConfig(poll_max=2048, snapshot_every=0),
            sinks=[MemorySink()])
        pub = attach_mesh(mesh.coordinator, refresh=0.2, start=False)
        mesh.start()
        serve = ServeServer(pub.store, port=0).start()
        gw = SnapshotGateway([pub.store], poll=60)
        gws = ServeServer(gw.store, port=0).start()
        gw.serve_on(gws)
        try:
            mesh.wait_idle()
            snap = pub.publish_now()
            assert snap.source == "mesh"
            assert gw.sync_once() == "full"
            assert gw.store.current.version == pub.store.current.version
            assert gw.store.current.source == "mesh"
            _assert_gateway_parity(serve.port, gws.port, pub.store)
        finally:
            gws.stop()
            serve.stop()
            mesh.finalize()


# ---- resync / damage -------------------------------------------------------


class TestGatewayResync:
    def test_gap_forces_full_resync_and_serving_survives(self):
        store = SnapshotStore()
        store.publish_snapshot(state_to_snapshot(_mk_state(1, bump=1)))
        feed = SnapshotFeed(store, history=1)
        gw = SnapshotGateway([feed], poll=60)
        assert gw.sync_once() == "full"
        v1 = gw.store.current.version
        # the upstream advances PAST the feed history without the
        # gateway observing the links -> its next poll cannot chain
        for v in (2, 3, 4):
            store.publish_snapshot(
                state_to_snapshot(_mk_state(v, bump=v)))
            feed.frame_since(v)  # another subscriber observed them
        assert gw.sync_once() == "full"  # history evicted -> full ship
        assert gw.store.current.version == 4 > v1

    def test_corrupt_frames_resync_without_unpublishing(self):
        store = SnapshotStore()
        store.publish_snapshot(state_to_snapshot(_mk_state(1, bump=1)))
        gw = SnapshotGateway([store], poll=60)
        assert gw.sync_once() == "full"
        up = gw.upstreams[0]
        good_fetch = up.fetch
        resyncs0 = gw._m["resyncs"].value(reason="crc")
        store.publish_snapshot(state_to_snapshot(_mk_state(2, bump=2)))
        up.fetch = lambda since: good_fetch(since)[:-2] + b"XX"
        assert gw.sync_once() == "resync"
        assert gw._m["resyncs"].value(reason="crc") == resyncs0 + 1
        # the serving store kept its last good snapshot
        assert gw.store.current.version == 1
        # transport healed: the next poll is since=0 -> full, and the
        # mirror lands on the upstream's current version
        up.fetch = good_fetch
        assert gw.sync_once() == "full"
        assert gw.store.current.version == 2

    def test_stale_or_replayed_full_never_moves_versions_backwards(self):
        store = SnapshotStore()
        store.publish_snapshot(state_to_snapshot(_mk_state(5, bump=5)))
        gw = SnapshotGateway([store], poll=60)
        gw.sync_once()
        assert gw.store.current.version == 5
        # a replayed older full frame (flapping upstream / proxy cache)
        stale = state_to_snapshot(_mk_state(3, bump=3))
        assert gw.store.publish_snapshot(stale) is None
        assert gw.store.current.version == 5

    def test_upstream_restart_is_counted_not_adopted(self):
        """An upstream that restarts republishes from v1 (its store is
        per-process). Deltas only move forward, so a refused publish is
        the restart signature: the replica keeps serving its
        pre-restart snapshot (monotone by construction) and
        gateway_upstream_restarts_total is the live wedge signal the
        GatewayUpstreamRestarted alert pages on."""
        store = SnapshotStore()
        for v in (1, 2, 3):
            store.publish_snapshot(state_to_snapshot(_mk_state(v, bump=v)))
        gw = SnapshotGateway([store], poll=60)
        assert gw.sync_once() == "full"
        assert gw.store.current.version == 3
        up = gw.upstreams[0]
        r0 = gw._m["upstream_restarts"].value(upstream=up.name)
        # the upstream process restarts: fresh store + feed, v1 again
        fresh = SnapshotStore()
        fresh.publish_snapshot(state_to_snapshot(_mk_state(1, bump=9)))
        up._feed = SnapshotFeed(fresh)
        assert gw.sync_once() == "full"       # the restart's full frame
        assert gw.store.current.version == 3  # ...is never adopted
        assert gw._m["upstream_restarts"].value(
            upstream=up.name) == r0 + 1
        # post-restart deltas keep signalling: a live wedge, not a blip
        fresh.publish_snapshot(state_to_snapshot(_mk_state(2, bump=10)))
        assert gw.sync_once() == "delta"
        assert gw.store.current.version == 3
        assert gw._m["upstream_restarts"].value(
            upstream=up.name) == r0 + 2

    def test_unreachable_upstream_raises_oserror_for_the_loop(self):
        gw = SnapshotGateway(["127.0.0.1:1"], poll=60, timeout=0.2)
        with pytest.raises(OSError):
            gw.sync_once()

    def test_upstream_dying_mid_response_is_a_poll_failure(self):
        """IncompleteRead/BadStatusLine are HTTPException, NOT OSError
        (the r17 member-transport lesson): an upstream severed
        mid-response must normalize into the poll loop's OSError
        outage handling, not kill the mirror thread."""
        import http.client as hc

        gw = SnapshotGateway(["127.0.0.1:1"], poll=60, timeout=0.2)
        up = gw.upstreams[0]

        class _DiesMidResponse:
            def request(self, *a, **k):
                pass

            def getresponse(self):
                raise hc.IncompleteRead(b"partial")

            def close(self):
                pass

        up.conn = _DiesMidResponse()
        with pytest.raises(OSError):
            gw.sync_once()
        assert up.conn is None  # the dead connection was evicted


# ---- consistent hashing + client -------------------------------------------


class TestHashRing:
    def test_deterministic_across_instances(self):
        a = HashRing(["n1:1", "n2:2", "n3:3"])
        b = HashRing(["n1:1", "n2:2", "n3:3"])
        for k in map(str, range(200)):
            assert a.node_for(k) == b.node_for(k)

    def test_kill_remaps_only_the_dead_arc(self):
        ring = HashRing(["n1:1", "n2:2", "n3:3"])
        keys = [f"key-{i}" for i in range(300)]
        before = {k: ring.node_for(k) for k in keys}
        after = {k: ring.node_for(k, skip={"n2:2"}) for k in keys}
        assert all(v != "n2:2" for v in after.values())
        for k in keys:
            if before[k] != "n2:2":
                assert after[k] == before[k], k  # survivors undisturbed
        assert {v for v in before.values()} == {"n1:1", "n2:2", "n3:3"}

    def test_client_fails_over_on_http_exception(self):
        """A replica killed MID-RESPONSE surfaces IncompleteRead /
        BadStatusLine — HTTPException, not OSError. The client's
        contract is 'retried elsewhere, never surfaced'."""
        import http.client as hc

        store = SnapshotStore()
        store.publish_snapshot(state_to_snapshot(_mk_state(1, bump=1)))
        srv = ServeServer(store, port=0).start()
        try:
            good = f"127.0.0.1:{srv.port}"
            bad = "127.0.0.1:59999"
            client = GatewayClient([good, bad])
            real = client._conn_for

            class _Boom:
                def request(self, *a, **k):
                    raise hc.BadStatusLine("killed mid-response")

                def close(self):
                    pass

            client._conn_for = \
                lambda node: _Boom() if node == bad else real(node)
            path = next(p for p in (f"/query/topk?k={i}"
                                    for i in range(100))
                        if client.ring.node_for(p) == bad)
            code, body = client.get(path)
            assert code == 200 and body
            assert client.retries >= 1
        finally:
            srv.stop()

    def test_spread_is_roughly_even(self):
        ring = HashRing([f"n{i}:{i}" for i in range(4)])
        counts: dict = {}
        for i in range(4000):
            n = ring.node_for(f"k{i}")
            counts[n] = counts.get(n, 0) + 1
        assert min(counts.values()) > 4000 / 4 / 3  # no starved node


# ---- replication / churn gates ---------------------------------------------


def _client_reader(client, stop, out, paths):
    last = 0
    i = 0
    while not stop.is_set():
        path = paths[i % len(paths)]
        i += 1
        try:
            code, doc = client.get_json(path)
        except (OSError, ValueError) as e:  # noqa: PERF203 -- teardown race at stop is fine
            if not stop.is_set():
                out["errors"].append(f"{path}: {e}")
            continue
        if code >= 500:
            out["errors"].append(f"{path}: {code}")
            continue
        v = (doc or {}).get("version", 0)
        if v and v < last:
            out["errors"].append(
                f"{path}: version went backwards {last}->{v}")
        last = max(last, v)
        out["n"] += 1


@pytest.mark.slow
class TestGatewayChurn:
    """Marked slow: these are the multi-second live-ingest churn soaks.
    They ALWAYS run in `make gateway-parity` (the CI step filters no
    markers); the tier-1 budget keeps the fast parity/codec gates."""

    def test_kill_one_gateway_is_invisible_to_clients(self):
        """THE replication gate: live ingest, two gateway replicas,
        4 client threads reading through the consistent-hash client;
        one replica dies mid-load — zero 5xx, zero surfaced errors,
        versions monotone, reads keep flowing and versions advance."""
        worker = StreamWorker(
            Consumer(_fill_bus(batches=24, per=500), fixedlen=True),
            _models(), [MemorySink()],
            WorkerConfig(snapshot_every=0, poll_max=256))
        pub = attach_worker(worker, refresh=0.05)
        serve = ServeServer(pub.store, port=0).start()

        gws, servers = [], []
        for _ in range(2):
            gw = SnapshotGateway([f"127.0.0.1:{serve.port}"], poll=0.02)
            srv = ServeServer(gw.store, port=0).start()
            gw.serve_on(srv)
            gws.append(gw)
            servers.append(srv)
        client = GatewayClient(
            [f"127.0.0.1:{s.port}" for s in servers], monotone_wait=5.0)
        stop = threading.Event()
        out = {"errors": [], "n": 0}
        paths = ("/query/topk?model=top_talkers&k=10", "/query/version",
                 "/query/range")
        ingest = threading.Thread(
            target=lambda: worker.run(stop_when_idle=True), daemon=True)
        readers = []
        try:
            ingest.start()
            for gw in gws:
                gw.start()
            deadline = time.monotonic() + 30
            while any(gw.store.current is None for gw in gws) and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            assert all(gw.store.current is not None for gw in gws)
            readers = [threading.Thread(
                target=_client_reader, args=(client, stop, out, paths),
                daemon=True) for _ in range(4)]
            for t in readers:
                t.start()
            time.sleep(0.4)  # readers overlap live ingest
            # kill the replica the ring actually routes traffic to —
            # killing an arc no path hashes onto would make the gate
            # vacuously green
            victim_node = client.ring.node_for(paths[0])
            victim = next(i for i, s in enumerate(servers)
                          if f"127.0.0.1:{s.port}" == victim_node)
            gws[victim].stop()
            servers[victim].stop()
            survivor = gws[1 - victim]
            time.sleep(0.4)
            n_after_kill = out["n"]
            ingest.join(timeout=120)
            with worker.lock:
                final = pub.publish(worker)
            deadline = time.monotonic() + 10
            while survivor.store.current.version < final.version and \
                    time.monotonic() < deadline:
                time.sleep(0.02)
            time.sleep(0.2)
        finally:
            stop.set()
            for t in readers:
                t.join(timeout=30)
            for i, gw in enumerate(gws):
                if i != victim:
                    gw.stop()
                    servers[i].stop()
            serve.stop()
        assert not out["errors"], out["errors"][:5]
        assert out["n"] > n_after_kill > 20  # reads flowed before AND after
        # the surviving replica reached the final upstream version
        assert survivor.store.current.version == final.version
        assert client.retries >= 1  # the failover actually happened

    def test_kill_one_mesh_worker_under_gateway_read_load(self):
        """THE mesh-churn gate through the gateway: readers hammer a
        gateway mirroring the coordinator's merged stream while a mesh
        MEMBER is killed — zero 5xx, versions monotone, merges keep
        landing and the gateway keeps advancing."""
        from flow_pipeline_tpu.mesh import InProcessMesh, produce_sharded
        from flow_pipeline_tpu.serve import attach_mesh

        def mesh_models():
            return {
                "flows_5m": WindowAggregator(
                    WindowAggConfig(batch_size=512)),
                "top_talkers": WindowedHeavyHitter(
                    HeavyHitterConfig(
                        key_cols=("src_addr", "dst_addr", "src_port",
                                  "dst_port", "proto"),
                        batch_size=512, width=1 << 12, capacity=128),
                    k=10),
            }

        bus = InProcessBus()
        bus.create_topic("flows", 4)
        gen = FlowGenerator(ZipfProfile(n_keys=200, alpha=1.3), seed=11,
                            t0=1_700_000_000, rate=25.0)
        done = 0
        while done < 16000:
            done += produce_sharded(bus, "flows", gen.batch(2048), 4)
        mesh = InProcessMesh(
            bus, "flows", 2, model_factory=mesh_models,
            config=WorkerConfig(poll_max=1024, snapshot_every=0),
            sinks=[], submit_every=2)
        pub = attach_mesh(mesh.coordinator, refresh=0.05, start=True)
        gw = SnapshotGateway([pub.store], poll=0.02).start()
        gws = ServeServer(gw.store, port=0).start()
        gw.serve_on(gws)
        client = GatewayClient([f"127.0.0.1:{gws.port}"])
        stop = threading.Event()
        out = {"errors": [], "n": 0}
        paths = ("/query/topk?model=top_talkers&k=10", "/query/version",
                 "/query/range")
        readers = []
        try:
            mesh.start()
            deadline = time.monotonic() + 30
            while gw.store.current is None and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            assert gw.store.current is not None
            readers = [threading.Thread(
                target=_client_reader, args=(client, stop, out, paths),
                daemon=True) for _ in range(4)]
            for t in readers:
                t.start()
            time.sleep(0.5)
            mesh.kill_member(1)  # fence + rebalance under read load
            mesh.wait_idle()
            v_before = gw.store.current.version
            pub.publish_now()
            deadline = time.monotonic() + 10
            while gw.store.current.version <= v_before and \
                    time.monotonic() < deadline:
                time.sleep(0.02)
            assert gw.store.current.version > v_before
        finally:
            stop.set()
            mesh.finalize()
            pub.stop()
            gw.stop()
            gws.stop()
        for t in readers:
            t.join(timeout=30)
        assert not out["errors"], out["errors"][:5]
        assert out["n"] > 50
        assert mesh.coordinator._m["rebalance"].value(
            reason="death") >= 1.0


# ---- chaos seam ------------------------------------------------------------


class TestGatewayChaos:
    def test_injected_poll_faults_ride_the_mirror_alive(self):
        """gateway.poll faults (flowchaos seam) surface as poll
        failures: the mirror keeps its last snapshot, versions stay
        monotone, and syncs resume when the plan disarms."""
        store = SnapshotStore()
        store.publish_snapshot(state_to_snapshot(_mk_state(1, bump=1)))
        gw = SnapshotGateway([store], poll=60)
        assert gw.sync_once() == "full"
        FAULTS.configure("gateway.poll:p=1@seed=3")
        store.publish_snapshot(state_to_snapshot(_mk_state(2, bump=2)))
        with pytest.raises(OSError):
            gw.sync_once()
        assert gw.store.current.version == 1  # kept serving
        FAULTS.configure(None)
        assert gw.sync_once() == "delta"
        assert gw.store.current.version == 2


# ---- flags / wiring --------------------------------------------------------


def test_gateway_flags_registered_and_parsed():
    from flow_pipeline_tpu.utils.flags import KNOWN_FLAGS, FlagSet

    assert {"gateway.listen", "gateway.upstream",
            "gateway.poll"} <= KNOWN_FLAGS
    fs = FlagSet("t")
    fs.string("gateway.upstream", "", "h")
    fs.string("gateway.listen", ":8084", "h")
    fs.number("gateway.poll", 0.25, "h")
    vals = fs.parse(["-gateway.upstream", "a:1,b:2",
                     "-gateway.poll", "0.1"])
    assert vals["gateway.upstream"] == "a:1,b:2"
    assert vals["gateway.poll"] == 0.1


def test_sub_snapshot_endpoint_serves_frames():
    """/sub/snapshot on a plain serve server: binary frames, correct
    kinds, and the JSON cache is untouched by the polls."""
    store = SnapshotStore()
    store.publish_snapshot(state_to_snapshot(_mk_state(1, bump=1)))
    serve = ServeServer(store, port=0).start()
    try:
        raw = _get_raw(serve.port, "/sub/snapshot?since=0")
        tree = next(decode_frames(raw))
        assert tree["t"] == "full" and tree["to"] == 1
        raw = _get_raw(serve.port, "/sub/snapshot?since=1")
        assert next(decode_frames(raw))["t"] == "none"
        assert serve._cache == {}  # never cached as JSON entries
    finally:
        serve.stop()
