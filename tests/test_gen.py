"""Generator tests: mocker behavior parity (bounds, address shape, sequence
numbers) and Zipf heavy-tail properties."""

import numpy as np

from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile, ZipfProfile
from flow_pipeline_tpu.schema.batch import words_to_addr


class TestMockerParity:
    def test_field_bounds(self):
        g = FlowGenerator(MockerProfile(), seed=1)
        b = g.batch(2048)
        c = b.columns
        assert c["bytes"].max() < 1500 and c["packets"].max() < 100
        assert set(np.unique(c["src_as"])) <= {65000, 65001, 65002}
        assert set(np.unique(c["dst_as"])) <= {65000, 65001, 65002}
        assert (c["etype"] == 0x86DD).all()
        assert (c["sampling_rate"] == 1).all()
        assert c["src_port"].max() < 2**16

    def test_addresses_in_prefix(self):
        g = FlowGenerator(MockerProfile(), seed=2)
        b = g.batch(256)
        addr = words_to_addr(b.columns["src_addr"][0])
        assert addr[:8] == bytes([0x20, 0x01, 0x0D, 0xB8, 0, 0, 0, 1])
        assert addr[8:15] == bytes(7)
        # only the last byte varies -> at most 256 distinct addresses
        distinct = {words_to_addr(w) for w in b.columns["src_addr"]}
        assert 1 < len(distinct) <= 256

    def test_sequence_and_time_monotonic(self):
        g = FlowGenerator(MockerProfile(), seed=3, rate=1000.0)
        b1, b2 = g.batch(100), g.batch(100)
        assert b1.columns["sequence_num"][0] == 0
        assert b2.columns["sequence_num"][0] == 100
        assert b1.columns["time_flow_start"][0] == b1.columns["time_received"][0]
        assert b2.columns["time_received"][0] >= b1.columns["time_received"][-1]

    def test_seeded_determinism(self):
        a = FlowGenerator(MockerProfile(), seed=7).batch(500)
        b = FlowGenerator(MockerProfile(), seed=7).batch(500)
        for name in a.columns:
            np.testing.assert_array_equal(a.columns[name], b.columns[name])
        c = FlowGenerator(MockerProfile(), seed=8).batch(500)
        assert any((a.columns[n] != c.columns[n]).any() for n in ("bytes", "src_as"))


class TestZipf:
    def test_heavy_tail(self):
        g = FlowGenerator(ZipfProfile(n_keys=1000, alpha=1.3), seed=5)
        b = g.batch(20000)
        # the hottest (src,dst) addr pair should dominate far beyond uniform
        pair = np.concatenate([b.columns["src_addr"], b.columns["dst_addr"]], axis=1)
        voided = np.ascontiguousarray(pair).view([("", np.uint32)] * 8).reshape(-1)
        _, counts = np.unique(voided, return_counts=True)
        assert counts.max() > 20000 / 1000 * 20  # >>20x the uniform share

    def test_key_universe_bounded(self):
        g = FlowGenerator(ZipfProfile(n_keys=50, alpha=1.0), seed=5)
        b = g.batch(5000)
        pair = np.concatenate([b.columns["src_addr"], b.columns["dst_addr"]], axis=1)
        voided = np.ascontiguousarray(pair).view([("", np.uint32)] * 8).reshape(-1)
        assert len(np.unique(voided)) <= 50

    def test_rate_fills_windows(self):
        g = FlowGenerator(ZipfProfile(), seed=5, t0=1_699_999_800, rate=100.0)  # 300-aligned
        b = g.batch(60_000)  # 600 seconds of traffic
        slots = np.unique(b.columns["time_received"] // 300)
        assert len(slots) == 2
