"""Fused native dataplane parity (-ingest.fused, native/flowfused.cc).

The single-pass group->cascade->sketch kernel must be BIT-EXACT against
the staged path it replaces — same flows_5m rows, same CMS counters,
same top-K tables, same DDoS alerts — across prefilter x admission x
family-cascade configurations (`make fused-parity` runs this file
against a freshly built library).

Layers:

- kernel parity: ff_group_sum vs ops.hostgroup.group_by_key(exact);
  ff_fused_update (single family, cascade chain, ddos side table) vs
  the staged HostSketchEngine fed numpy-grouped tables;
- pipeline parity: HostSketchPipeline(fused=on) vs fused=off vs
  HostGroupPipeline on the shared fused-test stream (window rolls +
  late rows), engine-state arrays compared bit-for-bit after sync;
- worker integration: identical sink rows fused vs staged, a
  checkpoint hand-off between the two modes, and the flag-validation
  error paths.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from flow_pipeline_tpu import native
from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.engine.hostfused import HostGroupPipeline
from flow_pipeline_tpu.hostsketch import HostSketchPipeline
from flow_pipeline_tpu.hostsketch.engine import HostSketchEngine
from flow_pipeline_tpu.models import (
    DDoSConfig,
    DDoSDetector,
    DenseTopConfig,
    DenseTopKModel,
    HeavyHitterConfig,
    WindowAggConfig,
    WindowAggregator,
)
from flow_pipeline_tpu.engine import WindowedHeavyHitter
from flow_pipeline_tpu.ops import hostgroup
from flow_pipeline_tpu.schema import wire
from flow_pipeline_tpu.transport import Consumer, InProcessBus

from test_fused import (
    BS,
    WINDOW,
    assert_same_windows,
    canon_rows,
    make_models,
    make_stream,
)

try:  # hypothesis gates ONLY the property run — parity runs regardless
    from hypothesis import given, settings, strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - CI installs hypothesis
    HAVE_HYPOTHESIS = False

pytestmark = pytest.mark.skipif(
    not native.fused_available(),
    reason="libflowdecode lacks the fused dataplane; run `make native`")


# ---- kernel layer ----------------------------------------------------------


class TestGroupSumKernel:
    @pytest.fixture
    def rng(self):
        return np.random.default_rng(11)

    @pytest.mark.parametrize("n,w,p", [(1, 1, 1), (257, 3, 2),
                                       (4096, 11, 3), (100, 2, 1)])
    def test_matches_exact_groupby(self, rng, n, w, p):
        lanes = rng.integers(0, 8, size=(n, w), dtype=np.uint32)
        vals = rng.integers(0, 1 << 20, size=(n, p), dtype=np.uint64)
        got = native.group_sum(lanes, vals)
        assert got is not None
        uniq, sums, counts = got
        ref_u, ref_s, ref_c = hostgroup.group_by_key(
            lanes, [vals], exact=True, native=True)
        np.testing.assert_array_equal(uniq, ref_u)
        np.testing.assert_array_equal(sums, ref_s[0])
        np.testing.assert_array_equal(counts, ref_c)

    def test_empty_batch(self):
        got = native.group_sum(np.zeros((0, 2), np.uint32),
                               np.zeros((0, 1), np.uint64))
        assert got is not None
        uniq, sums, counts = got
        assert uniq.shape == (0, 2) and sums.shape == (0, 1)
        assert counts.shape == (0,)

    def test_all_identical_rows(self):
        lanes = np.full((500, 4), 7, np.uint32)
        vals = np.full((500, 2), 3, np.uint64)
        uniq, sums, counts = native.group_sum(lanes, vals)
        assert uniq.shape == (1, 4)
        np.testing.assert_array_equal(sums, [[1500, 1500]])
        np.testing.assert_array_equal(counts, [500])

    def test_u64_sums_exact_at_scale(self, rng):
        # sums past 2^53 stay exact in the uint64 accumulator (the f64
        # path would round) — the flows_5m exactness contract
        lanes = np.zeros((4, 1), np.uint32)
        vals = np.full((4, 1), (1 << 62) // 4 + 1, np.uint64)
        _, sums, _ = native.group_sum(lanes, vals)
        assert sums[0, 0] == np.uint64((1 << 62) // 4 + 1) * np.uint64(4)


def np_group(lanes, planes):
    """Staged-reference grouping for sketch families (exact=False hash
    identity, hash-ascending order — what _group_families computes)."""
    return hostgroup.group_by_key(lanes, planes, exact=False, native=True)


def run_engine_reference(cfg, rounds, engine_mode="native"):
    """Feed numpy-grouped tables through the staged HostSketchEngine —
    the bit-exactness baseline the fused kernel must reproduce."""
    eng = HostSketchEngine([cfg], use_native=engine_mode)
    eng.reset(0)
    for lanes, vals in rounds:
        uniq, sums, counts = np_group(lanes, [vals])
        g = uniq.shape[0]
        s = np.zeros((g, vals.shape[1] + 1), np.float32)
        s[:, :vals.shape[1]] = sums[0]
        s[:, vals.shape[1]] = counts
        eng.update(0, uniq, s, g)
    return eng.states[0]


def single_family_plan(cfg):
    return native.FusedPlan(
        parent=np.asarray([-1], np.int64),
        sel=np.zeros(0, np.int64),
        sel_off=np.asarray([0, 0], np.int64),
        depth=np.asarray([cfg.depth], np.int64),
        width=np.asarray([cfg.width], np.int64),
        cap=np.asarray([cfg.capacity], np.int64),
        conservative=np.asarray([cfg.conservative], np.uint8),
        prefilter=np.asarray([cfg.table_prefilter], np.uint8),
        admission_plain=np.asarray([cfg.table_admission == "plain"],
                                   np.uint8),
    )


class TestFusedUpdateKernel:
    @pytest.fixture
    def rng(self):
        return np.random.default_rng(23)

    def _rounds(self, rng, n_rounds=3, n=900, w=4, p=2, keyspace=64):
        out = []
        for _ in range(n_rounds):
            lanes = rng.integers(0, keyspace, size=(n, w), dtype=np.uint32)
            vals = rng.integers(0, 1 << 12, size=(n, p)).astype(np.float32)
            out.append((lanes, vals))
        return out

    @pytest.mark.parametrize("prefilter", [True, False])
    @pytest.mark.parametrize("admission", ["est", "plain"])
    @pytest.mark.parametrize("conservative", [True, False])
    def test_single_family_vs_staged_engine(self, rng, prefilter,
                                            admission, conservative):
        # capacity 16 with a 64-key space: prefilter boundary (g > 2*cap)
        # is crossed every round, evictions happen, width 32 forces CMS
        # collisions; four scalar key cols = the 4 lanes _rounds builds
        cfg = HeavyHitterConfig(key_cols=("proto", "src_port", "dst_port",
                                          "etype"),
                                depth=2, width=32,
                                capacity=16, conservative=conservative,
                                table_prefilter=prefilter,
                                table_admission=admission, batch_size=BS)
        rounds = self._rounds(rng)
        ref = run_engine_reference(cfg, rounds)
        eng = HostSketchEngine([cfg], use_native="native")
        eng.reset(0)
        plan = single_family_plan(cfg)
        for lanes, vals in rounds:
            assert native.fused_update(lanes, vals, plan, [eng.states[0]],
                                       do_sketch=True, threads=2) is None
        np.testing.assert_array_equal(eng.states[0].cms, ref.cms)
        np.testing.assert_array_equal(eng.states[0].table_keys,
                                      ref.table_keys)
        np.testing.assert_array_equal(eng.states[0].table_vals,
                                      ref.table_vals)

    def test_capacity_one_table(self, rng):
        cfg = HeavyHitterConfig(key_cols=("proto",), depth=2, width=16,
                                capacity=1, batch_size=BS)
        rounds = self._rounds(rng, n=200, w=1, keyspace=8)
        ref = run_engine_reference(cfg, rounds)
        eng = HostSketchEngine([cfg], use_native="native")
        eng.reset(0)
        plan = single_family_plan(cfg)
        for lanes, vals in rounds:
            native.fused_update(lanes, vals, plan, [eng.states[0]],
                                do_sketch=True)
        np.testing.assert_array_equal(eng.states[0].table_keys,
                                      ref.table_keys)
        np.testing.assert_array_equal(eng.states[0].table_vals,
                                      ref.table_vals)

    def test_cascade_chain_and_ddos(self, rng):
        """Root [w=4] -> child selecting lanes (0,1) -> grandchild
        selecting child lane (1,) == root lane 1, plus the ddos side
        table off the child — vs the staged cascade in numpy."""
        def cfg_w(key_cols):
            return HeavyHitterConfig(key_cols=key_cols, depth=2, width=64,
                                     capacity=8, batch_size=BS)
        root_cfg = cfg_w(("proto", "src_port", "dst_port", "etype"))
        child_cfg = cfg_w(("proto", "src_port"))
        grand_cfg = cfg_w(("src_port",))
        plan = native.FusedPlan(
            parent=np.asarray([-1, 0, 1], np.int64),
            sel=np.asarray([0, 1, 1], np.int64),
            sel_off=np.asarray([0, 0, 2, 3], np.int64),
            depth=np.asarray([2, 2, 2], np.int64),
            width=np.asarray([64, 64, 64], np.int64),
            cap=np.asarray([8, 8, 8], np.int64),
            conservative=np.asarray([1, 1, 1], np.uint8),
            prefilter=np.asarray([1, 1, 1], np.uint8),
            admission_plain=np.asarray([0, 0, 0], np.uint8),
            ddos_parent=1, ddos_sel=np.asarray([0], np.int64),
            ddos_plane=1)
        engines = [HostSketchEngine([c], use_native="native")
                   for c in (root_cfg, child_cfg, grand_cfg)]
        for e in engines:
            e.reset(0)
        ref_engines = [HostSketchEngine([c], use_native="native")
                       for c in (root_cfg, child_cfg, grand_cfg)]
        for e in ref_engines:
            e.reset(0)
        for lanes, vals in self._rounds(rng, n=600, w=4, p=2, keyspace=16):
            states = [e.states[0] for e in engines]
            got = native.fused_update(lanes, vals, plan, states,
                                      do_sketch=True)
            # staged reference: numpy cascade, engine per family
            r_u, r_s, r_c = np_group(lanes, [vals])
            c_u, c_s, c_c64 = np_group(
                r_u[:, [0, 1]], [r_s[0], r_c.astype(np.uint64)])
            c_c = c_s[1].astype(np.int64)
            g_u, g_s, g_c64 = np_group(
                c_u[:, [1]], [c_s[0], c_c.astype(np.uint64)])
            g_c = g_s[1].astype(np.int64)
            for eng, (u, vs, cnt) in zip(
                    ref_engines, [(r_u, r_s[0], r_c), (c_u, c_s[0], c_c),
                                  (g_u, g_s[0], g_c)]):
                s = np.zeros((u.shape[0], 3), np.float32)
                s[:, :2] = vs
                s[:, 2] = cnt
                eng.update(0, u, s, u.shape[0])
            d_u, d_s, _ = np_group(c_u[:, [0]], [c_s[0][:, 1]])
            np.testing.assert_array_equal(got[0], d_u)
            np.testing.assert_array_equal(got[1],
                                          d_s[0].astype(np.float32))
        for eng, ref in zip(engines, ref_engines):
            np.testing.assert_array_equal(eng.states[0].cms,
                                          ref.states[0].cms)
            np.testing.assert_array_equal(eng.states[0].table_keys,
                                          ref.states[0].table_keys)
            np.testing.assert_array_equal(eng.states[0].table_vals,
                                          ref.states[0].table_vals)

    def test_do_sketch_false_leaves_state_untouched(self, rng):
        cfg = HeavyHitterConfig(key_cols=("proto",), depth=2, width=16,
                                capacity=4, batch_size=BS)
        base = single_family_plan(cfg)
        plan = native.FusedPlan(
            parent=base.parent, sel=base.sel, sel_off=base.sel_off,
            depth=base.depth, width=base.width, cap=base.cap,
            conservative=base.conservative, prefilter=base.prefilter,
            admission_plain=base.admission_plain,
            ddos_parent=0, ddos_sel=np.asarray([0], np.int64),
            ddos_plane=0)
        lanes = rng.integers(0, 8, size=(100, 1), dtype=np.uint32)
        vals = rng.integers(0, 100, size=(100, 1)).astype(np.float32)
        got = native.fused_update(lanes, vals, plan, None,
                                  do_sketch=False)
        d_u, d_s, _ = np_group(lanes, [vals[:, 0]])
        np.testing.assert_array_equal(got[0], d_u)
        np.testing.assert_array_equal(got[1], d_s[0].astype(np.float32))

    def test_do_ddos_false_skips_side_table_only(self, rng):
        """do_ddos=False (a late ddos sub-window discarding the table)
        must skip the per-dst cascade output while the sketch updates
        stay bit-identical to a gated-on pass."""
        cfg = HeavyHitterConfig(key_cols=("proto",), depth=2, width=16,
                                capacity=4, batch_size=BS)
        base = single_family_plan(cfg)
        plan = native.FusedPlan(
            parent=base.parent, sel=base.sel, sel_off=base.sel_off,
            depth=base.depth, width=base.width, cap=base.cap,
            conservative=base.conservative, prefilter=base.prefilter,
            admission_plain=base.admission_plain,
            ddos_parent=0, ddos_sel=np.asarray([0], np.int64),
            ddos_plane=0)
        lanes = rng.integers(0, 8, size=(100, 1), dtype=np.uint32)
        vals = rng.integers(0, 100, size=(100, 1)).astype(np.float32)
        engines = [HostSketchEngine([cfg], use_native="native")
                   for _ in range(2)]
        for e in engines:
            e.reset(0)
        on = native.fused_update(lanes, vals, plan,
                                 [engines[0].states[0]], do_sketch=True)
        off = native.fused_update(lanes, vals, plan,
                                  [engines[1].states[0]], do_sketch=True,
                                  do_ddos=False)
        assert on is not None and off is None
        np.testing.assert_array_equal(engines[0].states[0].cms,
                                      engines[1].states[0].cms)
        np.testing.assert_array_equal(engines[0].states[0].table_keys,
                                      engines[1].states[0].table_keys)
        np.testing.assert_array_equal(engines[0].states[0].table_vals,
                                      engines[1].states[0].table_vals)

    def test_degenerate_shapes_rejected(self):
        cfg = HeavyHitterConfig(key_cols=("proto",), depth=2, width=16,
                                capacity=4, batch_size=BS)
        plan = single_family_plan(cfg)
        bad = native.FusedPlan(  # root must have parent -1
            parent=np.asarray([0], np.int64), sel=np.zeros(0, np.int64),
            sel_off=np.asarray([0, 0], np.int64),
            depth=plan.depth, width=plan.width, cap=plan.cap,
            conservative=plan.conservative, prefilter=plan.prefilter,
            admission_plain=plan.admission_plain)
        eng = HostSketchEngine([cfg], use_native="native")
        eng.reset(0)
        lanes = np.zeros((4, 1), np.uint32)
        vals = np.zeros((4, 1), np.float32)
        with pytest.raises(ValueError, match="ff_fused_update"):
            native.fused_update(lanes, vals, bad, [eng.states[0]],
                                do_sketch=True)

    def test_out_of_range_lane_selection_rejected(self):
        """A sel (or ddos_sel) index past the parent's key width must be
        rejected before any state write — it would otherwise read
        out-of-bounds memory into the sketch."""
        cfg = HeavyHitterConfig(key_cols=("proto",), depth=2, width=16,
                                capacity=4, batch_size=BS)
        engines = [HostSketchEngine([cfg], use_native="native")
                   for _ in range(2)]
        for e in engines:
            e.reset(0)
        lanes = np.zeros((4, 1), np.uint32)
        vals = np.zeros((4, 1), np.float32)
        base = single_family_plan(cfg)
        bad_sel = native.FusedPlan(
            parent=np.asarray([-1, 0], np.int64),
            sel=np.asarray([5], np.int64),  # parent has 1 key lane
            sel_off=np.asarray([0, 0, 1], np.int64),
            depth=np.asarray([2, 2], np.int64),
            width=np.asarray([16, 16], np.int64),
            cap=np.asarray([4, 4], np.int64),
            conservative=np.asarray([1, 1], np.uint8),
            prefilter=np.asarray([1, 1], np.uint8),
            admission_plain=np.asarray([0, 0], np.uint8))
        with pytest.raises(ValueError, match="ff_fused_update"):
            native.fused_update(lanes, vals, bad_sel,
                                [e.states[0] for e in engines],
                                do_sketch=True)
        bad_ddos_sel = native.FusedPlan(
            parent=base.parent, sel=base.sel, sel_off=base.sel_off,
            depth=base.depth, width=base.width, cap=base.cap,
            conservative=base.conservative, prefilter=base.prefilter,
            admission_plain=base.admission_plain,
            ddos_parent=0, ddos_sel=np.asarray([-1], np.int64),
            ddos_plane=0)
        with pytest.raises(ValueError, match="ff_fused_update"):
            native.fused_update(lanes, vals, bad_ddos_sel,
                                [engines[0].states[0]], do_sketch=True)

    def test_empty_chunk_is_noop(self):
        cfg = HeavyHitterConfig(key_cols=("proto",), depth=2, width=16,
                                capacity=4, batch_size=BS)
        eng = HostSketchEngine([cfg], use_native="native")
        eng.reset(0)
        before = eng.states[0].cms.copy()
        native.fused_update(np.zeros((0, 1), np.uint32),
                            np.zeros((0, 1), np.float32),
                            single_family_plan(cfg), [eng.states[0]],
                            do_sketch=True)
        np.testing.assert_array_equal(eng.states[0].cms, before)


# ---- thread-count determinism (r19 flowspeed) ------------------------------
#
# The threading contract the whole fused dataplane leans on: every
# kernel's output is BIT-IDENTICAL at any thread count — the threaded
# hash-group (per-key-range partitioning + per-partition stable sort),
# the u64 wagg fold, the lane builders, and the full fused tree through
# ff_fused_update, table AND invertible. `make fused-parity` runs this
# sweep against a freshly built library.


class TestThreadDeterminism:
    @pytest.fixture
    def rng(self):
        return np.random.default_rng(41)

    # n=5000 crosses the serial gate (4096) with 3 row blocks; n=40000
    # spreads ~20 blocks over every worker; keyspace 50 forces heavy
    # duplicate rows ACROSS blocks, so the original-row-order tie-break
    # inside each hash group is actually exercised
    @pytest.mark.parametrize("threads", [2, 8])
    @pytest.mark.parametrize("n", [5000, 40000])
    def test_hash_group_mt_matches_serial(self, rng, threads, n):
        lanes = rng.integers(0, 50, size=(n, 3), dtype=np.uint32)
        perm, starts, coll = native.hash_group(lanes)
        perm_t, starts_t, coll_t = native.hash_group(lanes,
                                                     threads=threads)
        np.testing.assert_array_equal(perm_t, perm)
        np.testing.assert_array_equal(starts_t, starts)
        assert coll_t == coll

    @pytest.mark.parametrize("threads", [2, 8])
    def test_hash_group_mt_degenerate_shapes(self, threads):
        # one group spanning every block, and n unique groups — the two
        # partition-occupancy extremes
        same = np.full((8192, 2), 9, np.uint32)
        perm, starts, _ = native.hash_group(same, threads=threads)
        np.testing.assert_array_equal(perm, np.arange(8192, dtype=np.int32))
        np.testing.assert_array_equal(starts, [0])
        uniq = np.arange(8192, dtype=np.uint32)[:, None]
        p_ref, s_ref, _ = native.hash_group(uniq)
        p_t, s_t, _ = native.hash_group(uniq, threads=threads)
        np.testing.assert_array_equal(p_t, p_ref)
        np.testing.assert_array_equal(s_t, s_ref)

    @pytest.mark.parametrize("threads", [2, 8])
    def test_group_sum_mt_matches_serial(self, rng, threads):
        lanes = rng.integers(0, 64, size=(20000, 4), dtype=np.uint32)
        vals = rng.integers(0, 1 << 40, size=(20000, 2), dtype=np.uint64)
        uniq, sums, counts = native.group_sum(lanes, vals)
        u_t, s_t, c_t = native.group_sum(lanes, vals, threads=threads)
        np.testing.assert_array_equal(u_t, uniq)
        np.testing.assert_array_equal(s_t, sums)
        np.testing.assert_array_equal(c_t, counts)

    def _tree_state(self, rng, threads, invertible):
        """Drive the cascade+ddos tree at one thread count; return the
        per-family state arrays + the per-round ddos tables."""
        kwargs = dict(depth=2, width=64, capacity=8, batch_size=BS)
        if invertible:
            kwargs["hh_sketch"] = "invertible"
        cfgs = [HeavyHitterConfig(
                    key_cols=("proto", "src_port", "dst_port", "etype"),
                    **kwargs),
                HeavyHitterConfig(key_cols=("proto", "src_port"),
                                  **kwargs),
                HeavyHitterConfig(key_cols=("src_port",), **kwargs)]
        plan = native.FusedPlan(
            parent=np.asarray([-1, 0, 1], np.int64),
            sel=np.asarray([0, 1, 1], np.int64),
            sel_off=np.asarray([0, 0, 2, 3], np.int64),
            depth=np.asarray([2, 2, 2], np.int64),
            width=np.asarray([64, 64, 64], np.int64),
            cap=np.asarray([8, 8, 8], np.int64),
            conservative=np.asarray([0 if invertible else 1] * 3,
                                    np.uint8),
            prefilter=np.asarray([1, 1, 1], np.uint8),
            admission_plain=np.asarray([0, 0, 0], np.uint8),
            ddos_parent=1, ddos_sel=np.asarray([0], np.int64),
            ddos_plane=1,
            invertible=np.asarray([invertible] * 3, np.uint8))
        engines = [HostSketchEngine([c], use_native="native")
                   for c in cfgs]
        for e in engines:
            e.reset(0)
        ddos = []
        for _ in range(3):
            lanes = rng.integers(0, 16, size=(6000, 4), dtype=np.uint32)
            vals = rng.integers(0, 1 << 12, size=(6000, 2)) \
                      .astype(np.float32)
            states = [e.states[0] for e in engines]
            ddos.append(native.fused_update(lanes, vals, plan, states,
                                            do_sketch=True,
                                            threads=threads))
        return engines, ddos

    @pytest.mark.parametrize("invertible", [False, True],
                             ids=["table", "invertible"])
    @pytest.mark.parametrize("threads", [2, 8])
    def test_fused_tree_thread_sweep(self, threads, invertible):
        """The full fused tree — cascade chain + ddos side table —
        bit-identical between threads=1 and every swept count, for both
        sketch families (6000 rows crosses the kernel's serial gates)."""
        if invertible and not native.inv_available():
            pytest.skip("libflowdecode lacks the invertible kernels")
        ref_e, ref_d = self._tree_state(np.random.default_rng(43), 1,
                                        invertible)
        got_e, got_d = self._tree_state(np.random.default_rng(43),
                                        threads, invertible)
        for eng, ref in zip(got_e, ref_e):
            s, r = eng.states[0], ref.states[0]
            np.testing.assert_array_equal(s.cms, r.cms)
            if invertible:
                np.testing.assert_array_equal(s.keysum, r.keysum)
                np.testing.assert_array_equal(s.keycheck, r.keycheck)
            else:
                np.testing.assert_array_equal(s.table_keys, r.table_keys)
                np.testing.assert_array_equal(s.table_vals, r.table_vals)
        for got, ref in zip(got_d, ref_d):
            np.testing.assert_array_equal(got[0], ref[0])
            np.testing.assert_array_equal(got[1], ref[1])

    @pytest.mark.slow  # full e2e sweep (~7s); gated by `make fused-parity`
    @pytest.mark.parametrize("threads", [2, 8])
    @pytest.mark.parametrize("hh_sketch", ["table", "invertible"])
    @pytest.mark.parametrize("fused", ["on", "off"])
    def test_pipeline_thread_sweep(self, fused, hh_sketch, threads):
        """End-to-end: the full pipeline (window rolls + late rows),
        through ff_fused_update (fused=on) AND the staged path
        (fused=off), emits identical windows and engine state at every
        thread count — -ingest.threads is purely a throughput knob."""
        if hh_sketch == "invertible" and not native.inv_available():
            pytest.skip("libflowdecode lacks the invertible kernels")
        batches = make_stream()
        ref, rp = drive(cfg_models(hh_sketch=hh_sketch), batches,
                        fused=fused, threads=1)
        got, gp = drive(cfg_models(hh_sketch=hh_sketch), batches,
                        fused=fused, threads=threads)
        assert gp._engine.threads == threads
        for (name, w), (_, w2) in zip(rp._hh, gp._hh):
            np.testing.assert_array_equal(
                np.asarray(w.model.state.cms),
                np.asarray(w2.model.state.cms),
                err_msg=f"{name} cms @ {threads} threads")
        assert_models_identical(ref, got)


# ---- pipeline layer --------------------------------------------------------


def cfg_models(prefilter=True, admission="est", capacity=128,
               families="cascade", hh_sketch="table"):
    """The test model family with configurable sketch knobs. families=
    "cascade" includes the 5-tuple parent the IP families regroup from;
    "flat" keeps only the (own, own) IP families; "noddos" drops the
    detector; "minimal" is flows_5m + ddos only (the ddos-"own" path);
    "nodense" is hh + cascade ddos with NO dense model — the chunk whose
    staged inputs are all None and only fused_in carries work (the
    apply() skip-condition regression)."""
    def hh_cfg(key_cols):
        return HeavyHitterConfig(
            key_cols=key_cols, batch_size=BS, width=1 << 10,
            capacity=capacity, table_prefilter=prefilter,
            table_admission=admission)

    models = {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=BS))}
    if hh_sketch != "table":
        base_cfg = hh_cfg

        def hh_cfg(key_cols):  # noqa: F811 -- shadow with the family flip
            return dataclasses.replace(base_cfg(key_cols),
                                       hh_sketch=hh_sketch)
    if families != "minimal":
        if families in ("cascade", "nodense"):
            models["top_talkers"] = WindowedHeavyHitter(
                hh_cfg(("src_addr", "dst_addr", "src_port", "dst_port",
                        "proto")), k=50)
        models["top_src_ips"] = WindowedHeavyHitter(
            hh_cfg(("src_addr",)), k=50)
        models["top_dst_ips"] = WindowedHeavyHitter(
            hh_cfg(("dst_addr",)), k=50)
        if families != "nodense":
            models["top_src_ports"] = WindowedHeavyHitter(
                DenseTopConfig(key_col="src_port", batch_size=BS), k=50,
                model_cls=DenseTopKModel)
    if families != "noddos":
        models["ddos_alerts"] = DDoSDetector(DDoSConfig(
            n_buckets=1 << 10, sub_window_seconds=WINDOW,
            warmup_windows=0, batch_size=BS))
    return models


def drive(models, batches, **kw):
    pipe = HostSketchPipeline(models, **kw)
    for b in batches:
        pipe.update(b)
    pipe.sync_states()
    return models, pipe


def assert_models_identical(a: dict, b: dict):
    assert canon_rows(a["flows_5m"].flush(True)) == \
        canon_rows(b["flows_5m"].flush(True))
    for name in a:
        m = a[name]
        if isinstance(m, WindowedHeavyHitter):
            assert_same_windows(m.flush(True), b[name].flush(True))
            assert m.late_flows_dropped == b[name].late_flows_dropped
    if "ddos_alerts" in a:
        fa, ha = a["ddos_alerts"], b["ddos_alerts"]
        assert fa.late_flows_dropped == ha.late_flows_dropped
        assert len(fa.alerts) == len(ha.alerts)
        for x, y in zip(fa.alerts, ha.alerts):
            assert x.keys() == y.keys()
            for k in x:
                np.testing.assert_array_equal(np.asarray(x[k]),
                                              np.asarray(y[k]))


class TestPipelineParity:
    @pytest.mark.parametrize("prefilter", [True, False])
    @pytest.mark.parametrize("admission", ["est", "plain"])
    def test_bit_exact_vs_staged(self, prefilter, admission):
        batches = make_stream()
        staged, sp = drive(cfg_models(prefilter, admission), batches,
                           fused="off")
        fused, fp = drive(cfg_models(prefilter, admission), batches,
                          fused="on")
        assert fp._fused and not sp._fused
        assert_models_identical(staged, fused)

    @pytest.mark.parametrize("families", ["flat", "noddos", "minimal",
                                          "nodense"])
    def test_family_plan_shapes(self, families):
        """Multiple own-rooted trees (flat), no detector riding the
        cascade (noddos), no hh families at all (minimal — the
        ddos-"own" grouping stays on the staged path), and no dense
        model (nodense — the prepared chunk's staged inputs are ALL
        None, so only fused_in keeps apply() from skipping the chunk;
        regression for the silent-drop bug)."""
        batches = make_stream()
        staged, _ = drive(cfg_models(families=families), batches,
                          fused="off")
        fused, fp = drive(cfg_models(families=families), batches,
                          fused="on")
        assert fp._fused
        assert_models_identical(staged, fused)

    def test_capacity_one_eviction_storm(self):
        batches = make_stream()
        staged, _ = drive(cfg_models(capacity=1), batches, fused="off")
        fused, _ = drive(cfg_models(capacity=1), batches, fused="on")
        assert_models_identical(staged, fused)

    def test_engine_state_bit_exact_mid_stream(self):
        """CMS counters and top-K tables — not just flushed windows —
        must match after a partial stream (sync_states exports them)."""
        batches = make_stream()[:3]  # open window, nothing flushed
        staged, sp = drive(make_models(WINDOW, 100), batches, fused="off")
        fused, fp = drive(make_models(WINDOW, 100), batches, fused="on")
        for (name, w), (_, w2) in zip(sp._hh, fp._hh):
            s, f = w.model.state, w2.model.state
            np.testing.assert_array_equal(
                np.asarray(s.cms), np.asarray(f.cms),
                err_msg=f"{name} cms")
            np.testing.assert_array_equal(
                np.asarray(s.table_keys), np.asarray(f.table_keys),
                err_msg=f"{name} table_keys")
            np.testing.assert_array_equal(
                np.asarray(s.table_vals), np.asarray(f.table_vals),
                err_msg=f"{name} table_vals")

    def test_vs_hostgrouped_device_pipeline(self):
        """Transitively: fused == staged == the jitted device apply."""
        batches = make_stream()

        def drive_dev(models):
            pipe = HostGroupPipeline(models)
            for b in batches:
                pipe.update(b)
            return models

        dev = drive_dev(make_models(WINDOW, 100))
        fused, _ = drive(make_models(WINDOW, 100), batches, fused="on")
        assert_models_identical(dev, fused)

    def test_bad_fused_mode_rejected(self):
        with pytest.raises(ValueError, match="fused"):
            HostSketchPipeline(make_models(WINDOW, 100), fused="fast")

    def test_fused_on_requires_native_engine(self):
        with pytest.raises(RuntimeError, match="fused"):
            HostSketchPipeline(make_models(WINDOW, 100), fused="on",
                               sketch_native="numpy")

    if HAVE_HYPOTHESIS:
        @settings(max_examples=8, deadline=None)
        @given(seed=st.integers(0, 2**16), n_keys=st.integers(2, 400))
        def test_random_streams_property(self, seed, n_keys):
            from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile

            gen = FlowGenerator(ZipfProfile(n_keys=n_keys, alpha=1.1),
                                seed=seed)
            t0 = 6000
            batches = []
            for i in range(3):
                b = gen.batch(BS)
                b.columns["time_received"] = (
                    t0 + i * 120 + (np.arange(BS) % 40)).astype(np.uint64)
                batches.append(b)
            staged, _ = drive(cfg_models(capacity=32), batches,
                              fused="off")
            fused, _ = drive(cfg_models(capacity=32), batches, fused="on")
            assert_models_identical(staged, fused)


# ---- worker layer ----------------------------------------------------------


class CollectSink:
    def __init__(self):
        self.rows: dict[str, list] = {}

    def write(self, table, rows):
        self.rows.setdefault(table, []).append(rows)


def _canon_table(chunks) -> list:
    out = []
    for rows in chunks:
        if isinstance(rows, dict):
            out.extend(canon_rows(rows))
        else:
            out.extend(tuple(sorted((k, str(v)) for k, v in r.items()))
                       for r in rows)
    return sorted(out)


def _run_worker(fused_mode, batches, ckpt=None, snapshot_every=0,
                restore=False):
    bus = InProcessBus()
    bus.create_topic("flows", 1)
    for b in batches:
        for frame in wire.iter_raw_frames(b.to_wire()):
            bus.produce("flows", frame)
    sink = CollectSink()
    worker = StreamWorker(
        Consumer(bus, fixedlen=True), make_models(WINDOW, 100), [sink],
        WorkerConfig(poll_max=BS, snapshot_every=snapshot_every,
                     checkpoint_path=ckpt, sketch_backend="host",
                     ingest_fused=fused_mode),
    )
    if restore:
        assert worker.restore()
    worker.run(stop_when_idle=True)
    return worker, sink


class TestWorkerIntegration:
    def test_worker_sink_rows_fused_vs_staged(self):
        batches = make_stream()
        worker, fused = _run_worker("on", batches)
        assert isinstance(worker.fused, HostSketchPipeline)
        assert worker.fused._fused
        _, staged = _run_worker("off", batches)
        assert set(fused.rows) == set(staged.rows)
        for table in fused.rows:
            assert _canon_table(fused.rows[table]) == \
                _canon_table(staged.rows[table]), f"table {table} diverged"

    @pytest.mark.parametrize("first,second", [("on", "off"),
                                              ("off", "on")])
    def test_checkpoint_mode_switch(self, tmp_path, first, second):
        """Snapshot under one dataplane mode, restore under the other:
        engine state re-imports transparently, rows stay identical."""
        batches = make_stream()
        ck = str(tmp_path / "ck")
        _, ref1 = _run_worker(first, batches[:4], ckpt=str(
            tmp_path / "ck_ref"), snapshot_every=1)
        _, ref2 = _run_worker(first, batches[4:], ckpt=str(
            tmp_path / "ck_ref"), restore=True)
        _, got1 = _run_worker(first, batches[:4], ckpt=ck,
                              snapshot_every=1)
        _, got2 = _run_worker(second, batches[4:], ckpt=ck, restore=True)
        for ref, got in ((ref1, got1), (ref2, got2)):
            assert set(ref.rows) == set(got.rows)
            for table in ref.rows:
                assert _canon_table(ref.rows[table]) == \
                    _canon_table(got.rows[table]), \
                    f"{first}->{second}: table {table} diverged"

    def test_fused_on_needs_host_backend(self):
        with pytest.raises(ValueError, match="ingest_fused"):
            StreamWorker(None, {}, [],
                         WorkerConfig(sketch_backend="device",
                                      ingest_fused="on"))

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="ingest_fused"):
            StreamWorker(None, {}, [],
                         WorkerConfig(ingest_fused="always"))
