"""Host sketch dataplane parity (flow_pipeline_tpu.hostsketch).

The `-sketch.backend=host` engine must be BIT-EXACT against the jitted
path on the uint64-exact envelope (integer-valued counters, per-cell
totals < 2^24 where f32 is exact): CMS counters, top-K tables, and
flows_5m rows — enforced here, never eyeballed (`make
hostsketch-parity` runs this file against a freshly built library).

Layers:

- op parity: the numpy twin AND the native kernels vs ops.cms /
  ops.topk on random streams (hypothesis) and adversarial ones —
  high-collision narrow-CMS (every key collides), eviction-boundary
  ties at the table's capacity edge;
- pipeline parity: HostSketchPipeline vs HostGroupPipeline on the
  shared fused-test stream (window boundaries + late rows included);
- worker integration: identical sink rows device vs host, checkpoint
  round-trip with a backend SWITCH at restore in both directions.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from flow_pipeline_tpu import native
from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.engine.fused import FusedPipeline
from flow_pipeline_tpu.engine.hostfused import HostGroupPipeline
from flow_pipeline_tpu.hostsketch import HostSketchPipeline
from flow_pipeline_tpu.hostsketch import engine as hs_engine
from flow_pipeline_tpu.hostsketch.state import (
    from_device_state,
    to_device_state,
)
from flow_pipeline_tpu.models.heavy_hitter import (
    HeavyHitterConfig,
    _apply_grouped,
    hh_init,
)
from flow_pipeline_tpu.ops import cms as cms_ops
from flow_pipeline_tpu.ops import topk as topk_ops
from flow_pipeline_tpu.schema import wire
from flow_pipeline_tpu.transport import Consumer, InProcessBus

from test_fused import (
    BS,
    WINDOW,
    assert_same_windows,
    canon_rows,
    make_models,
    make_stream,
)

try:  # hypothesis gates ONLY the property test — parity runs regardless
    from hypothesis import given, settings, strategies as st
    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - CI installs hypothesis
    HAVE_HYPOTHESIS = False

NATIVE = native.sketch_available()
ENGINES = ["numpy"] + (["native"] if NATIVE else [])


def cms_ref(keys, vals, valid, conservative, width, depth=2, rounds=1):
    """Jitted reference: f32 CMS after `rounds` updates."""
    planes = vals.shape[1]
    c = cms_ops.cms_init(planes, depth, width)
    fn = cms_ops.cms_add_conservative if conservative else cms_ops.cms_add
    for r in range(1, rounds + 1):
        c = fn(c, jnp.asarray(keys), jnp.asarray(vals * r),
               jnp.asarray(valid))
    return np.asarray(c)


def cms_host(keys, vals, valid, conservative, width, engine, depth=2,
             rounds=1):
    planes = vals.shape[1]
    c = np.zeros((planes, depth, width), np.uint64)
    for r in range(1, rounds + 1):
        if engine == "native":
            native.hs_cms_update(c, keys, vals * r, valid, conservative,
                                 threads=4)
        else:
            hs_engine.np_cms_update(c, keys[valid], (vals * r)[valid],
                                    conservative)
    return c


class TestCMSParity:
    @pytest.mark.parametrize("engine", ENGINES)
    @pytest.mark.parametrize("conservative", [False, True])
    def test_narrow_cms_forced_collisions(self, rng, engine, conservative):
        """Adversarial: width 4 — every key collides with many others in
        every depth row, the regime where plain-vs-conservative and
        scatter ordering would diverge if anything were order-sensitive."""
        n = 300
        keys = rng.integers(0, 40, size=(n, 3), dtype=np.int64) \
            .astype(np.uint32)
        vals = rng.integers(0, 2000, size=(n, 2)).astype(np.float32)
        valid = rng.random(n) > 0.15
        # unique keys per call (the cms_add contract: pre-aggregated)
        keys, idx = np.unique(keys, axis=0, return_index=True)
        vals, valid = vals[idx], valid[idx]
        ref = cms_ref(keys, vals, valid, conservative, width=4, rounds=3)
        got = cms_host(keys, vals, valid, conservative, width=4,
                       engine=engine, rounds=3)
        np.testing.assert_array_equal(got.astype(np.float32), ref)
        # query parity on the updated sketch
        q_ref = np.asarray(cms_ops.cms_query(jnp.asarray(ref),
                                             jnp.asarray(keys)))
        if engine == "native":
            q = native.hs_cms_query(got, keys, threads=2)
        else:
            q = hs_engine.np_cms_query(got, keys)
        np.testing.assert_array_equal(q, q_ref)

    def test_native_matches_numpy_at_every_thread_count(self, rng):
        """Thread-count independence: the native engine's documented
        determinism claim, checked directly."""
        if not NATIVE:
            pytest.skip("native hostsketch engine not built")
        n = 500
        keys = np.unique(rng.integers(0, 60, size=(n, 4), dtype=np.int64)
                         .astype(np.uint32), axis=0)
        vals = rng.integers(0, 999, size=(keys.shape[0], 3)) \
            .astype(np.float32)
        for conservative in (False, True):
            want = None
            for threads in (1, 2, 5, 8):
                c = np.zeros((3, 4, 32), np.uint64)
                native.hs_cms_update(c, keys, vals, None, conservative,
                                     threads)
                if want is None:
                    want = c
                else:
                    np.testing.assert_array_equal(c, want)

    def test_degenerate_shapes_rejected(self):
        if not NATIVE:
            pytest.skip("native hostsketch engine not built")
        keys = np.zeros((1, 2), np.uint32)
        vals = np.ones((1, 1), np.float32)
        with pytest.raises(ValueError):  # zero-width sketch
            native.hs_cms_update(np.zeros((1, 1, 0), np.uint64), keys,
                                 vals, None, True, 1)
        # n == 0 is a clean no-op, not an error
        c = np.zeros((1, 2, 8), np.uint64)
        native.hs_cms_update(c, np.zeros((0, 2), np.uint32),
                             np.zeros((0, 1), np.float32), None, True, 1)
        assert c.sum() == 0


if HAVE_HYPOTHESIS:

    class TestRandomStreamProperty:
        @pytest.mark.parametrize("engine", ENGINES)
        @given(data=st.data())
        @settings(max_examples=30, deadline=None)
        def test_random_streams(self, engine, data):
            """Hypothesis: random key/value/validity streams, both update
            rules, random narrow widths — host CMS == device CMS
            bit-exactly (the satellite's random leg; the adversarial legs
            above run everywhere)."""
            rng = np.random.default_rng(
                data.draw(st.integers(0, 2**32 - 1)))
            n = data.draw(st.integers(1, 120))
            kw = data.draw(st.integers(1, 5))
            width = data.draw(st.sampled_from([2, 8, 64, 256]))
            conservative = data.draw(st.booleans())
            keys = rng.integers(0, 30, size=(n, kw), dtype=np.int64) \
                .astype(np.uint32)
            keys = np.unique(keys, axis=0)
            m = keys.shape[0]
            vals = rng.integers(0, 4000, size=(m, 2)).astype(np.float32)
            valid = rng.random(m) > 0.2
            ref = cms_ref(keys, vals, valid, conservative, width=width)
            got = cms_host(keys, vals, valid, conservative, width=width,
                           engine=engine)
            np.testing.assert_array_equal(got.astype(np.float32), ref)


def merge_ref(tk, tv, ck, cs, ce, cv):
    nk, nv = topk_ops.topk_merge_est(
        jnp.asarray(tk), jnp.asarray(tv), jnp.asarray(ck),
        jnp.asarray(cs), jnp.asarray(ce), jnp.asarray(cv))
    return np.asarray(nk), np.asarray(nv)


class TestTopKMergeParity:
    def _roundtrip(self, rng, engine, cap, kw, rounds, key_lo, key_hi,
                   tie_values=False):
        planes = 3
        tk0, tv0 = topk_ops.topk_init(cap, kw, planes)
        rk, rv = np.asarray(tk0), np.asarray(tv0)
        hk = rk.copy()
        hv = rv.copy()
        for _ in range(rounds):
            m = rng.integers(1, 3 * cap + 2)
            ck = rng.integers(key_lo, key_hi, size=(m, kw),
                              dtype=np.int64).astype(np.uint32)
            ck = np.unique(ck, axis=0)
            m = ck.shape[0]
            if tie_values:
                # eviction-boundary adversary: many equal primaries so
                # survival at rank C is decided purely by the tie-break
                cs = np.full((m, planes), 7.0, np.float32)
                ce = np.full((m, planes), 7.0, np.float32)
            else:
                cs = rng.integers(0, 500, size=(m, planes)) \
                    .astype(np.float32)
                ce = cs + rng.integers(0, 90, size=(m, planes)) \
                    .astype(np.float32)
            cv = rng.random(m) > 0.2
            rk, rv = merge_ref(rk, rv, ck, cs, ce, cv)
            if engine == "native":
                native.hs_topk_merge(hk, hv, ck, cs, ce, cv)
            else:
                hk, hv = hs_engine.np_topk_merge(hk, hv, ck[cv], cs[cv],
                                                 ce[cv])
        np.testing.assert_array_equal(hk, rk)
        np.testing.assert_array_equal(hv, rv)

    @pytest.mark.parametrize("engine", ENGINES)
    def test_random_rounds(self, rng, engine):
        self._roundtrip(rng, engine, cap=16, kw=3, rounds=8,
                        key_lo=0, key_hi=10)

    @pytest.mark.parametrize("engine", ENGINES)
    def test_eviction_boundary_ties(self, rng, engine):
        """All-equal primaries: which keys hold the last table slots is
        pure tie-break (lex order through the stable rank) — the case a
        sloppy reimplementation gets wrong first."""
        self._roundtrip(rng, engine, cap=8, kw=2, rounds=6,
                        key_lo=0, key_hi=6, tie_values=True)

    @pytest.mark.parametrize("engine", ENGINES)
    def test_capacity_one_table(self, rng, engine):
        self._roundtrip(rng, engine, cap=1, kw=2, rounds=5,
                        key_lo=0, key_hi=4)

    @pytest.mark.parametrize("engine", ENGINES)
    def test_all_sentinel_key_dropped(self, engine):
        """The all-1s key tuple marks empty slots and is unrepresentable;
        both backends must drop it from candidates identically."""
        cap, kw, planes = 4, 2, 2
        tk, tv = (np.asarray(a) for a in topk_ops.topk_init(cap, kw,
                                                            planes))
        ck = np.array([[0xFFFFFFFF, 0xFFFFFFFF], [1, 2]], np.uint32)
        cs = np.array([[9.0, 1.0], [5.0, 1.0]], np.float32)
        cv = np.ones(2, bool)
        rk, rv = merge_ref(tk, tv, ck, cs, cs, cv)
        hk, hv = tk.copy(), tv.copy()
        if engine == "native":
            native.hs_topk_merge(hk, hv, ck, cs, cs, cv)
        else:
            hk, hv = hs_engine.np_topk_merge(hk, hv, ck, cs, cs)
        np.testing.assert_array_equal(hk, rk)
        np.testing.assert_array_equal(hv, rv)


class TestApplyGroupedParity:
    """The full per-family step (CMS -> prefilter -> admission merge)
    vs models.heavy_hitter._apply_grouped, padded shapes included."""

    @pytest.mark.parametrize("engine", ENGINES)
    @pytest.mark.parametrize("admission", ["est", "plain"])
    @pytest.mark.parametrize("prefilter", [True, False])
    def test_grouped_step(self, rng, engine, admission, prefilter):
        cfg = HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr"), width=256, depth=3,
            capacity=8, batch_size=BS, table_prefilter=prefilter,
            table_admission=admission)
        eng = hs_engine.HostSketchEngine(
            [cfg], use_native=engine)
        state = hh_init(cfg)
        for _ in range(4):
            b = 64  # padded group-table size > 2*capacity: prefilter arms
            g = int(rng.integers(1, b + 1))
            uniq = np.zeros((b, 8), np.uint32)
            uniq[:g] = np.unique(
                rng.integers(0, 9, size=(b, 8), dtype=np.int64),
                axis=0)[:g].astype(np.uint32)
            g = len(np.unique(uniq[:g], axis=0))
            uniq[:g] = np.unique(uniq[:g], axis=0)
            sums = np.zeros((b, 3), np.float32)
            sums[:g] = rng.integers(0, 300, size=(g, 3))
            valid = np.zeros(b, bool)
            valid[:g] = True
            state = _apply_grouped(state, jnp.asarray(uniq),
                                   jnp.asarray(sums), jnp.asarray(valid),
                                   cfg)
            eng.update(0, uniq, sums, g)
        host = eng.export_state(0)
        np.testing.assert_array_equal(host.cms, np.asarray(state.cms))
        np.testing.assert_array_equal(host.table_keys,
                                      np.asarray(state.table_keys))
        np.testing.assert_array_equal(host.table_vals,
                                      np.asarray(state.table_vals))


class TestStateRoundTrip:
    def test_device_host_device_lossless(self, rng):
        cfg = HeavyHitterConfig(key_cols=("src_addr",), width=64,
                                capacity=4, batch_size=BS)
        state = hh_init(cfg)
        uniq = rng.integers(0, 50, size=(16, 4), dtype=np.int64) \
            .astype(np.uint32)
        uniq = np.unique(uniq, axis=0)
        sums = rng.integers(1, 100, size=(uniq.shape[0], 3)) \
            .astype(np.float32)
        state = _apply_grouped(state, jnp.asarray(uniq),
                               jnp.asarray(sums),
                               jnp.ones(uniq.shape[0], bool), cfg)
        back = to_device_state(from_device_state(state))
        np.testing.assert_array_equal(back.cms, np.asarray(state.cms))
        np.testing.assert_array_equal(back.table_keys,
                                      np.asarray(state.table_keys))
        np.testing.assert_array_equal(back.table_vals,
                                      np.asarray(state.table_vals))

    def test_import_clamps_out_of_envelope(self):
        st_dict = {
            "cms": np.array([[[np.inf, -3.0, np.nan, 5.0]]], np.float32),
            "table_keys": np.zeros((1, 1), np.uint32),
            "table_vals": np.zeros((1, 1), np.float32),
        }
        host = from_device_state(st_dict)
        assert host.cms[0, 0, 1] == 0 and host.cms[0, 0, 2] == 0
        assert host.cms[0, 0, 3] == 5
        assert host.cms[0, 0, 0] > np.uint64(1) << np.uint64(60)


def drive(pipeline_cls, models, batches, **kw):
    pipe = pipeline_cls(models, **kw)
    for b in batches:
        pipe.update(b)
    if hasattr(pipe, "sync_states"):
        pipe.sync_states()
    return models


class TestPipelineParity:
    @pytest.mark.parametrize("engine", ENGINES)
    def test_bit_exact_vs_hostgrouped(self, engine):
        """The full model family on the shared fused-test stream (window
        rolls + late rows): flows_5m, every sketch family, dense ports,
        DDoS — all bit-identical to the device-backend pipeline."""
        batches = make_stream()
        dev = drive(HostGroupPipeline, make_models(WINDOW, 100), batches)
        host = drive(HostSketchPipeline, make_models(WINDOW, 100),
                     batches, sketch_native=engine)
        assert canon_rows(dev["flows_5m"].flush(True)) == \
            canon_rows(host["flows_5m"].flush(True))
        for name in ("top_talkers", "top_src_ips", "top_dst_ips",
                     "top_src_ports"):
            assert_same_windows(dev[name].flush(True),
                                host[name].flush(True))
            assert dev[name].late_flows_dropped == \
                host[name].late_flows_dropped
        fa, ha = dev["ddos_alerts"], host["ddos_alerts"]
        assert fa.late_flows_dropped == ha.late_flows_dropped
        assert len(fa.alerts) == len(ha.alerts)
        for x, y in zip(fa.alerts, ha.alerts):
            assert x.keys() == y.keys()
            for k in x:
                np.testing.assert_array_equal(np.asarray(x[k]),
                                              np.asarray(y[k]))

    def test_engine_rejects_unknown_mode(self):
        with pytest.raises(ValueError, match="use_native"):
            hs_engine.HostSketchEngine([], use_native="fast")


class CollectSink:
    def __init__(self):
        self.rows: dict[str, list] = {}

    def write(self, table, rows):
        self.rows.setdefault(table, []).append(rows)


def _canon_table(chunks) -> list:
    out = []
    for rows in chunks:
        if isinstance(rows, dict):
            out.extend(canon_rows(rows))
        else:  # list of alert dicts
            out.extend(tuple(sorted((k, str(v)) for k, v in r.items()))
                       for r in rows)
    return sorted(out)


def _run_worker(backend, batches, ckpt=None, snapshot_every=0,
                restore=False):
    bus = InProcessBus()
    bus.create_topic("flows", 1)
    for b in batches:
        for frame in wire.iter_raw_frames(b.to_wire()):
            bus.produce("flows", frame)
    sink = CollectSink()
    worker = StreamWorker(
        Consumer(bus, fixedlen=True), make_models(WINDOW, 100), [sink],
        WorkerConfig(poll_max=BS, snapshot_every=snapshot_every,
                     checkpoint_path=ckpt, sketch_backend=backend),
    )
    if restore:
        assert worker.restore()
    worker.run(stop_when_idle=True)
    return worker, sink


class TestWorkerIntegration:
    def test_worker_sink_rows_device_vs_host(self):
        batches = make_stream()
        _, dev = _run_worker("device", batches)
        worker, host = _run_worker("host", batches)
        assert isinstance(worker.fused, HostSketchPipeline)
        assert set(dev.rows) == set(host.rows)
        for table in dev.rows:
            assert _canon_table(dev.rows[table]) == \
                _canon_table(host.rows[table]), f"table {table} diverged"

    def test_host_backend_needs_host_grouping(self):
        """host_assist off -> the host engine has no group tables to
        consume; the worker must fall back to the device step loudly,
        not crash or silently change semantics."""
        worker = StreamWorker(
            None, make_models(WINDOW, 100), [],
            WorkerConfig(sketch_backend="host", host_assist="off"))
        assert isinstance(worker.fused, FusedPipeline)
        assert not isinstance(worker.fused, HostGroupPipeline)

    def test_bad_backend_rejected(self):
        with pytest.raises(ValueError, match="sketch_backend"):
            StreamWorker(None, {}, [],
                         WorkerConfig(sketch_backend="gpu"))

    def test_open_window_topk_after_sync(self):
        """The live query path: mid-window (nothing closed or finalized
        yet) the host backend's model state is engine-resident; after
        sync_sketch_states() the open-window top-K must equal the device
        backend's bit-for-bit (what /topk serves)."""
        batches = make_stream()[:3]  # one open slot, no closes
        tops = {}
        for backend in ("device", "host"):
            bus = InProcessBus()
            bus.create_topic("flows", 1)
            for b in batches:
                for frame in wire.iter_raw_frames(b.to_wire()):
                    bus.produce("flows", frame)
            worker = StreamWorker(
                Consumer(bus, fixedlen=True), make_models(WINDOW, 100),
                [],
                WorkerConfig(poll_max=BS, snapshot_every=0,
                             sketch_backend=backend,
                             ingest_mode="serial", prefetch=0),
            )
            while worker.run_once():  # drive WITHOUT finalize: the
                pass                  # window stays open, sketch live
            with worker.lock:
                worker.sync_sketch_states()
                tops[backend] = worker.models["top_talkers"].model.top(20)
        for k in tops["device"]:
            np.testing.assert_array_equal(
                np.asarray(tops["device"][k]), np.asarray(tops["host"][k]),
                err_msg=f"topk column {k!r}")

    @pytest.mark.parametrize("first,second", [("device", "host"),
                                              ("host", "device")])
    def test_checkpoint_backend_switch(self, tmp_path, first, second):
        """Snapshot under one backend, restore under the other, finish
        the stream: final sink rows must equal an unswitched run — the
        state conversions are lossless, so a backend switch at restore
        is invisible downstream."""
        batches = make_stream()
        ck = str(tmp_path / "ck")
        # reference: the whole stream under the FIRST backend, split into
        # the same two worker lifetimes (finalize force-flushes tails, so
        # the split itself must match — only the backend may differ)
        _, ref1 = _run_worker(first, batches[:4], ckpt=str(
            tmp_path / "ck_ref"), snapshot_every=1)
        _, ref2 = _run_worker(first, batches[4:], ckpt=str(
            tmp_path / "ck_ref"), restore=True)
        # switched: same split, second half under the OTHER backend
        _, got1 = _run_worker(first, batches[:4], ckpt=ck,
                              snapshot_every=1)
        _, got2 = _run_worker(second, batches[4:], ckpt=ck, restore=True)
        for ref, got in ((ref1, got1), (ref2, got2)):
            assert set(ref.rows) == set(got.rows)
            for table in ref.rows:
                assert _canon_table(ref.rows[table]) == \
                    _canon_table(got.rows[table]), \
                    f"{first}->{second}: table {table} diverged"


class TestScatterBranchParity:
    """r20 degraded-mode fast path: the numpy twin's two scatter
    implementations — ufunc.at (numpy >= 1.25 indexed loops) and the
    grouped sort+reduceat rescue for older numpy — must be bit-exact
    twins, and the bucket-reuse engine step must not drift from either.
    u64 wrap sums and maxes are order-free, so any divergence is a bug,
    not a rounding story."""

    def _chunks(self, cfg, n_chunks=6, b=1024, seed=3):
        rng = np.random.default_rng(seed)
        from flow_pipeline_tpu.hostsketch.state import (host_hh_init,
                                                         host_inv_init)
        kw = host_hh_init(cfg).table_keys.shape[1] \
            if cfg.hh_sketch == "table" else \
            host_inv_init(cfg).keysum.shape[2]
        out = []
        for _ in range(n_chunks):
            uniq = np.zeros((b, kw), np.uint32)
            uniq[:, :5] = rng.integers(0, 2**32, size=(b, 5),
                                       dtype=np.int64).astype(np.uint32)
            planes = 3 if cfg.hh_sketch == "table" else 3
            sums = rng.random((b, planes)).astype(np.float32) * 1e4
            if cfg.hh_sketch == "invertible":
                sums[:, -1] = 1.0  # count plane
            out.append((uniq, sums))
        return out

    def _fold(self, cfg, chunks, grouped):
        old = hs_engine._GROUPED_SCATTER
        hs_engine._GROUPED_SCATTER = grouped
        try:
            eng = hs_engine.HostSketchEngine([cfg], use_native="numpy")
            eng.reset(0)
            for uniq, sums in chunks:
                eng.update(0, uniq, sums, uniq.shape[0])
        finally:
            hs_engine._GROUPED_SCATTER = old
        return eng.states[0]

    @pytest.mark.parametrize("conservative", [True, False])
    def test_table_family_branches_bit_exact(self, conservative):
        cfg = HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr", "src_port", "dst_port",
                      "proto"),
            batch_size=1024, width=1 << 10, capacity=128,
            conservative=conservative)
        chunks = self._chunks(cfg)
        a = self._fold(cfg, chunks, grouped=False)
        b = self._fold(cfg, chunks, grouped=True)
        np.testing.assert_array_equal(a.cms, b.cms)
        np.testing.assert_array_equal(a.table_keys, b.table_keys)
        np.testing.assert_array_equal(a.table_vals, b.table_vals)

    def test_invertible_family_branches_bit_exact(self):
        cfg = HeavyHitterConfig(
            key_cols=("src_addr", "dst_addr", "src_port", "dst_port",
                      "proto"),
            batch_size=1024, width=1 << 10, hh_sketch="invertible")
        chunks = self._chunks(cfg)
        a = self._fold(cfg, chunks, grouped=False)
        b = self._fold(cfg, chunks, grouped=True)
        np.testing.assert_array_equal(a.cms, b.cms)
        np.testing.assert_array_equal(a.keysum, b.keysum)
        np.testing.assert_array_equal(a.keycheck, b.keycheck)

    def test_bucket_reuse_matches_fresh_hash(self):
        """np_cms_update/query with caller-precomputed buckets must
        bit-equal the self-hashing call — the reuse is the r20 degraded
        fast path's main lever."""
        rng = np.random.default_rng(5)
        keys = rng.integers(0, 2**32, size=(512, 11),
                            dtype=np.int64).astype(np.uint32)
        vals = rng.random((512, 3)).astype(np.float32) * 100
        buckets = hs_engine._np_buckets(keys, 4, 1 << 10)
        for conservative in (True, False):
            a = np.zeros((3, 4, 1 << 10), np.uint64)
            b = np.zeros((3, 4, 1 << 10), np.uint64)
            hs_engine.np_cms_update(a, keys, vals, conservative)
            hs_engine.np_cms_update(b, keys, vals, conservative,
                                    buckets=buckets)
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(
            hs_engine.np_cms_query(a, keys),
            hs_engine.np_cms_query(a, keys, buckets))
