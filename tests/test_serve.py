"""flowserve tests: versioned-snapshot query serving (serve/).

The contracts pinned here, per docs/ARCHITECTURE.md "flowserve":

- snapshot-served /query/topk and /query/range are BIT-EXACT against
  the locked-path answer / the sink-committed rows at the same consumed
  point — single worker AND merged mesh;
- the read path acquires NO dataplane lock (worker.lock, coordinator
  _lock/_merge_lock are instrumented and must count zero);
- the legacy /topk serves lock-free from a fresh snapshot and falls
  back to the locked path the moment the snapshot is stale;
- snapshots are immutable and versions monotone under churn: 8 reader
  threads hammering /query/* during live ingest (and a mesh member
  kill) never see a torn response or a 5xx.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from flow_pipeline_tpu.engine import (StreamWorker, WindowedHeavyHitter,
                                      WorkerConfig)
from flow_pipeline_tpu.engine.query_api import QueryServer
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.models import (DenseTopConfig, DenseTopKModel,
                                      HeavyHitterConfig, WindowAggConfig,
                                      WindowAggregator)
from flow_pipeline_tpu.serve import (RangeLedger, ServeServer,
                                     SnapshotStore, attach_mesh,
                                     attach_worker)
from flow_pipeline_tpu.sink import MemorySink
from flow_pipeline_tpu.sink.base import rows_to_records
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer

T0 = 1_699_999_800  # window-aligned stream start


def _fill_bus(batches=8, per=500, rate=5.0, seed=91, partitions=1):
    """Pre-produced stream spanning several 5-minute windows (rate=5
    flows/s of modeled time -> 8x500 flows cover ~800s = 2 closed + 1
    open window)."""
    bus = InProcessBus()
    bus.create_topic("flows", partitions)
    gen = FlowGenerator(ZipfProfile(n_keys=100, alpha=1.3), seed=seed,
                        t0=T0, rate=rate)
    prod = Producer(bus, fixedlen=True)
    for _ in range(batches):
        prod.send_many(gen.batch(per).to_messages())
    return bus


def _models():
    return {
        "flows_5m": WindowAggregator(WindowAggConfig(batch_size=512)),
        "top_talkers": WindowedHeavyHitter(
            HeavyHitterConfig(batch_size=512, width=1 << 12, capacity=64),
            k=10),
        "top_src_ports": WindowedHeavyHitter(
            DenseTopConfig(key_col="src_port", batch_size=512), k=10,
            model_cls=DenseTopKModel),
    }


class _LockProbe:
    """Context-manager lock wrapper counting acquisitions — the
    read-path-takes-no-dataplane-lock instrument."""

    def __init__(self, inner):
        self.inner = inner
        self.count = 0

    def __enter__(self):
        self.count += 1
        return self.inner.__enter__()

    def __exit__(self, *a):
        return self.inner.__exit__(*a)

    def acquire(self, *a, **kw):
        self.count += 1
        return self.inner.acquire(*a, **kw)

    def release(self):
        return self.inner.release()


def _get(port, path):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}").read())


@pytest.fixture(scope="module")
def served():
    """Quiesced worker + publisher + flowserve server + locked-path
    query server, with the final snapshot published at the exact
    consumed point."""
    sink = MemorySink()
    worker = StreamWorker(
        Consumer(_fill_bus(), fixedlen=True), _models(), [sink],
        WorkerConfig(snapshot_every=0, poll_max=512))
    pub = attach_worker(worker, refresh=0.0)  # window-close only
    while worker.run_once():
        pass
    with worker.lock:
        pub.publish(worker)
    serve = ServeServer(pub.store, port=0).start()
    query = QueryServer(worker, port=0, serve=pub.store).start()
    yield worker, pub, serve, query, sink
    serve.stop()
    query.stop()


# ---- unit: store + ledger --------------------------------------------------


class TestSnapshotStore:
    def test_versions_monotone_and_swap_atomic(self):
        store = SnapshotStore()
        assert store.current is None
        s1 = store.publish(watermark=1.0, flows_seen=10, source="worker",
                           families={}, ranges={})
        s2 = store.publish(watermark=2.0, flows_seen=20, source="worker",
                           families={}, ranges={})
        assert (s1.version, s2.version) == (1, 2)
        assert store.current is s2
        assert s1.flows_seen == 10  # published objects never mutate

    def test_range_ledger_splits_retains_and_freezes(self):
        led = RangeLedger(["flows_5m"], max_slots=2)
        def rows(slots, base):
            return {"timeslot": np.asarray(slots, np.uint64),
                    "bytes": np.asarray(base, np.uint64)}
        led.write("flows_5m", rows([100, 100, 400], [1, 2, 3]))
        led.write("flows_5m", rows([400], [4]))       # late partial
        led.write("top_talkers", rows([100], [9]))    # not a range table
        led.write("flows_5m", rows([700], [5]))       # evicts slot 100
        frozen = led.freeze()
        assert list(frozen) == ["flows_5m"]
        slots = dict(frozen["flows_5m"])
        assert sorted(slots) == [400, 700]
        assert slots[400]["bytes"].tolist() == [3, 4]  # partials concat
        assert led.generation == 3


# ---- single worker ---------------------------------------------------------


class TestWorkerServe:
    def test_version_endpoint(self, served):
        worker, pub, serve, _, _ = served
        v = _get(serve.port, "/query/version")
        assert v["version"] == pub.store.current.version
        assert v["flows_seen"] == worker.flows_seen
        assert v["source"] == "worker"
        assert v["models"]["top_talkers"]["kind"] == "hh"
        assert v["models"]["top_src_ports"]["kind"] == "dense"
        assert v["ranges"]["flows_5m"]  # closed windows are served

    @pytest.mark.parametrize("qs", ["?k=1", "?k=5", "?k=10",
                                    "?model=top_src_ports&k=7"])
    def test_topk_bit_exact_vs_locked_path(self, served, qs):
        """Acceptance: the snapshot-served answer equals the locked
        read at the same consumed point, for every k and family kind."""
        worker, _, serve, query, _ = served
        snap_ans = _get(serve.port, f"/query/topk{qs}")
        with worker.lock:
            worker.sync_sketch_states()
            name = snap_ans["model"]
            m = worker.models[name]
            locked = rows_to_records({
                k: v[:snap_ans["k"]] for k, v in m.model.top(10).items()})
        assert snap_ans["rows"] == locked
        assert snap_ans["window_start"] == m.current_slot
        # and over HTTP: the legacy endpoint's locked-shape answer
        legacy = _get(query.port, f"/topk{qs}")
        assert legacy["rows"] == snap_ans["rows"]

    def test_cms_capture_is_host_resident_and_released(self, served):
        """Donation safety: hh_update donates its state buffers, so the
        published capture must be HOST numpy pulled at publish time (a
        lazily-read device array would be deleted by the next batch on
        TPU/GPU — invisible on CPU, where donation is ignored); after
        the first freeze the capture is released."""
        worker, pub, serve, _, _ = served
        with worker.lock:
            pub.publish(worker)
        fam = pub.store.current.families["top_talkers"]
        captured = fam.cms._thunk.__defaults__[0]
        assert isinstance(captured, np.ndarray)
        frozen = fam.cms.get()
        assert frozen.dtype == np.uint64
        assert fam.cms._thunk is None  # capture released after freeze
        assert fam.cms.get() is frozen  # memoized

    def test_estimate_is_the_frozen_cms_query(self, served):
        from flow_pipeline_tpu.hostsketch.engine import np_cms_query_u64

        _, pub, serve, _, _ = served
        fam = pub.store.current.families["top_talkers"]
        lanes = np.concatenate([np.atleast_1d(fam.rows["src_addr"][0]),
                                np.atleast_1d(fam.rows["dst_addr"][0])])
        key = ",".join(str(int(x)) for x in lanes)
        est = _get(serve.port, f"/query/estimate?key={key}")
        want = np_cms_query_u64(
            fam.cms.get(), np.asarray([lanes], np.uint32))[0]
        assert est["estimates"] == {"bytes": int(want[0]),
                                    "packets": int(want[1]),
                                    "count": int(want[2])}
        # CMS estimates upper-bound the table's observed sums
        assert est["estimates"]["bytes"] >= int(fam.rows["bytes"][0])

    def test_range_bit_exact_vs_sink_rows(self, served):
        """Acceptance: /query/range returns exactly what the sinks were
        given for the same closed slots."""
        _, _, serve, _, sink = served
        r = _get(serve.port, "/query/range")
        assert r["model"] == "flows_5m" and len(r["slots"]) >= 2
        for slot in r["slots"]:
            got = [x for x in r["rows"] if x["timeslot"] == slot]
            want = [x for x in sink.tables["flows_5m"]
                    if x["timeslot"] == slot]
            assert got == want and want
        # slot filtering
        lo = r["slots"][-1]
        one = _get(serve.port, f"/query/range?from={lo}&to={lo + 300}")
        assert one["slots"] == [lo]
        assert one["rows"] == [x for x in r["rows"]
                               if x["timeslot"] == lo]

    def test_read_path_acquires_no_dataplane_lock(self, served):
        """Acceptance: readers never touch worker.lock — instrumented."""
        worker, _, serve, _, _ = served
        probe = _LockProbe(worker.lock)
        worker.lock = probe
        try:
            fam = _get(serve.port, "/query/version")
            for path in ("/query/topk?k=10", "/query/range",
                         "/query/version", "/healthz",
                         "/query/topk?model=top_src_ports&k=3"):
                for _ in range(3):
                    _get(serve.port, path)
        finally:
            worker.lock = probe.inner
        assert probe.count == 0
        assert fam["version"] >= 1

    def test_legacy_topk_fresh_snapshot_skips_the_lock(self, served):
        worker, _, _, query, _ = served
        probe = _LockProbe(worker.lock)
        worker.lock = probe
        try:
            ans = _get(query.port, "/topk?k=5")
        finally:
            worker.lock = probe.inner
        assert probe.count == 0
        assert len(ans["rows"]) == 5

    def test_legacy_topk_stale_snapshot_falls_back_locked(self, served):
        """Freshness is the consumed point: any unpublished progress
        must route /topk back through the lock (and the two answers
        still agree once re-published)."""
        worker, pub, _, query, _ = served
        worker.flows_seen += 1  # simulate un-published progress
        probe = _LockProbe(worker.lock)
        worker.lock = probe
        try:
            ans = _get(query.port, "/topk?k=5")
        finally:
            worker.lock = probe.inner
            worker.flows_seen -= 1
        assert probe.count == 1  # the locked path served it
        assert len(ans["rows"]) == 5
        # k beyond the snapshot depth also falls back (served locked)
        deep = _get(query.port, "/topk?k=11")
        assert len(deep["rows"]) == 11

    def test_cache_etag_and_304(self, served):
        worker, pub, serve, _, _ = served
        hits0 = pub.store.m_cache_hits.value()
        url = f"http://127.0.0.1:{serve.port}/query/topk?k=4"
        r1 = urllib.request.urlopen(url)
        etag = r1.headers["ETag"]
        body1 = r1.read()
        r2 = urllib.request.urlopen(url)
        assert r2.headers["ETag"] == etag and r2.read() == body1
        assert pub.store.m_cache_hits.value() > hits0
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                url, headers={"If-None-Match": etag}))
        assert e.value.code == 304
        # a new publish swaps the pointer -> cache invalidates wholesale
        with worker.lock:
            pub.publish(worker)
        r3 = urllib.request.urlopen(url)
        assert r3.headers["ETag"] != etag
        # same data (consumed point unchanged), new version stamp
        doc1, doc3 = json.loads(body1), json.loads(r3.read())
        assert doc3["version"] > doc1["version"]
        assert doc3["rows"] == doc1["rows"]

    def test_errors(self, served):
        _, _, serve, query, _ = served
        for path, code in (("/nope", 404),
                           ("/query/topk?k=abc", 400),
                           ("/query/topk?k=-1", 400),
                           ("/query/topk?model=ghost", 400),
                           ("/query/estimate?key=1", 400),
                           ("/query/estimate", 400),
                           # out-of-range lanes: a numpy OverflowError
                           # must not abort the keep-alive connection
                           ("/query/estimate?key=-1,2,3,4,5,6,7,8",
                            400),
                           ("/query/estimate?key=4294967296,2,3,4,5,"
                            "6,7,8", 400),
                           ("/query/estimate?model=top_src_ports"
                            "&key=1", 400),
                           ("/query/range?model=ghost", 400),
                           ("/query/range?from=abc", 400)):
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(serve.port, path)
            assert e.value.code == code, path
        # satellite regression: malformed k on the LEGACY endpoint is a
        # 400 JSON error, not a handler traceback
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(query.port, "/topk?k=abc")
        assert e.value.code == 400
        assert "error" in json.loads(e.value.read())

    def test_503_before_first_publish(self):
        store = SnapshotStore()
        serve = ServeServer(store, port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(serve.port, "/query/topk")
            assert e.value.code == 503
            assert _get(serve.port, "/healthz")["version"] == 0
        finally:
            serve.stop()

    def test_worker_publishes_at_window_close_and_finalize(self):
        """The on_batch trigger: one publish per window close (plus the
        first batch and the finalize view) without any refresh cadence."""
        worker = StreamWorker(
            Consumer(_fill_bus(seed=17), fixedlen=True), _models(), [],
            WorkerConfig(snapshot_every=0, poll_max=512))
        pub = attach_worker(worker, refresh=0.0)
        worker.run(stop_when_idle=True)  # incl. finalize
        snap = pub.store.current
        # first batch + >=2 window closes + finalize
        assert snap.version >= 4
        assert snap.flows_seen == worker.flows_seen
        # finalize force-closed every window: all slots are served
        assert len(snap.ranges["flows_5m"]) >= 3


# ---- churn: snapshot immutability under concurrent readers -----------------


def _reader(port, stop, out, paths):
    """Hammer /query/* until stop; record (version per response,
    status codes, consistency violations)."""
    last_version = 0
    i = 0
    while not stop.is_set():
        path = paths[i % len(paths)]
        i += 1
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10)
            doc = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            if e.code >= 500:
                out["errors"].append(f"{path}: {e.code}")
            continue
        except OSError as e:  # noqa: PERF203 -- server teardown race at stop is fine
            if not stop.is_set():
                out["errors"].append(f"{path}: {e}")
            continue
        v = doc.get("version", 0)
        if v < last_version:
            out["errors"].append(
                f"{path}: version went backwards {last_version}->{v}")
        last_version = v
        if "rows" in doc and "k" in doc and len(doc["rows"]) > doc["k"]:
            out["errors"].append(f"{path}: more rows than k")
        if path.startswith("/query/range"):
            bad = [r for r in doc["rows"]
                   if r["timeslot"] not in doc["slots"]]
            if bad:
                out["errors"].append(f"{path}: row outside slots")
        out["n"] += 1


class TestChurn:
    def test_worker_ingest_with_8_readers(self):
        """8 reader threads hammer every endpoint while the worker
        ingests and publishes full-rate: every response is internally
        one version, versions are monotone per reader, zero 5xx."""
        worker = StreamWorker(
            Consumer(_fill_bus(batches=24, per=500, seed=23),
                     fixedlen=True),
            _models(), [],
            WorkerConfig(snapshot_every=0, poll_max=512))
        pub = attach_worker(worker, refresh=0.05)
        serve = ServeServer(pub.store, port=0).start()
        with worker.lock:
            pub.publish(worker)  # readers never see bootstrap 503s
        stop = threading.Event()
        out = {"errors": [], "n": 0}
        paths = ("/query/topk?k=10", "/query/version", "/query/range",
                 "/query/topk?model=top_src_ports&k=5")
        readers = [threading.Thread(target=_reader,
                                    args=(serve.port, stop, out, paths),
                                    daemon=True) for _ in range(8)]
        for t in readers:
            t.start()
        try:
            worker.run(stop_when_idle=True)
        finally:
            stop.set()
            for t in readers:
                t.join(timeout=30)
            serve.stop()
        assert not out["errors"], out["errors"][:5]
        assert out["n"] > 50  # the load was real
        assert pub.store.current.version > 1  # publishes kept landing


# ---- merged mesh -----------------------------------------------------------


def _mesh_models():
    return {
        "flows_5m": WindowAggregator(WindowAggConfig(batch_size=512)),
        "top_talkers": WindowedHeavyHitter(
            HeavyHitterConfig(
                key_cols=("src_addr", "dst_addr", "src_port",
                          "dst_port", "proto"),
                batch_size=512, width=1 << 12, capacity=128),
            k=10),
    }


def _mesh_bus(partitions=4, flows=8000, rate=40.0, seed=7):
    from flow_pipeline_tpu.mesh import produce_sharded

    bus = InProcessBus()
    bus.create_topic("flows", partitions)
    gen = FlowGenerator(ZipfProfile(n_keys=200, alpha=1.3), seed=seed,
                        t0=1_700_000_000, rate=rate)
    done = 0
    while done < flows:
        done += produce_sharded(bus, "flows", gen.batch(2048), partitions)
    return bus


class TestMeshServe:
    def test_merged_snapshot_parity_and_no_coordinator_lock(self):
        """Acceptance, mesh leg: the published MERGED snapshot answers
        /query/topk bit-exact vs the per-query fan-out (query_topk) and
        /query/range bit-exact vs the coordinator's sink rows — and the
        read path takes neither coordinator lock."""
        from flow_pipeline_tpu.mesh import InProcessMesh

        sink = MemorySink()
        mesh = InProcessMesh(
            _mesh_bus(), "flows", 2, model_factory=_mesh_models,
            config=WorkerConfig(poll_max=2048, snapshot_every=0),
            sinks=[sink])
        pub = attach_mesh(mesh.coordinator, refresh=0.2, start=False)
        mesh.start()
        serve = ServeServer(pub.store, port=0).start()
        try:
            mesh.wait_idle()
            snap = pub.publish_now()
            direct = mesh.coordinator.query_topk("top_talkers", 10)
            # stop the member threads (their heartbeats legitimately
            # take _lock — the instrument below must see READERS only)
            mesh._stop.set()
            for th in mesh._threads:
                th.join(timeout=60)
            c = mesh.coordinator
            probes = {"_lock": _LockProbe(c._lock),
                      "_merge_lock": _LockProbe(c._merge_lock)}
            c._lock, c._merge_lock = probes["_lock"], \
                probes["_merge_lock"]
            try:
                t = _get(serve.port, "/query/topk?model=top_talkers"
                                     "&k=10")
                r = _get(serve.port, "/query/range")
                _get(serve.port, "/query/version")
            finally:
                c._lock = probes["_lock"].inner
                c._merge_lock = probes["_merge_lock"].inner
            assert t["rows"] == direct["rows"] and t["rows"]
            assert t["window_start"] == direct["window_start"]
            assert snap.source == "mesh"
            for slot in r["slots"]:
                got = [x for x in r["rows"] if x["timeslot"] == slot]
                want = [x for x in sink.tables["flows_5m"]
                        if x["timeslot"] == slot]
                assert got == want and want
            assert probes["_lock"].count == 0
            assert probes["_merge_lock"].count == 0
        finally:
            serve.stop()
            mesh.finalize()

    def test_mesh_churn_kill_member_with_8_readers(self):
        """Satellite: 8 readers hammer the merged serving surface while
        the mesh ingests AND one member is killed mid-stream — zero
        5xx, versions monotone, merges keep publishing after the
        rebalance."""
        from flow_pipeline_tpu.mesh import InProcessMesh

        mesh = InProcessMesh(
            _mesh_bus(flows=16000, rate=25.0, seed=11), "flows", 2,
            model_factory=_mesh_models,
            config=WorkerConfig(poll_max=1024, snapshot_every=0),
            sinks=[], submit_every=2)
        pub = attach_mesh(mesh.coordinator, refresh=0.05, start=True)
        serve = ServeServer(pub.store, port=0).start()
        import time as _time

        stop = threading.Event()
        out = {"errors": [], "n": 0}
        readers = []
        paths = ("/query/topk?model=top_talkers&k=10", "/query/version",
                 "/query/range")
        try:
            mesh.start()
            # first publish before the readers go (no bootstrap 503s)
            deadline = _time.monotonic() + 30
            while pub.store.current is None and \
                    _time.monotonic() < deadline:
                _time.sleep(0.01)
            assert pub.store.current is not None
            readers = [threading.Thread(
                target=_reader, args=(serve.port, stop, out, paths),
                daemon=True) for _ in range(8)]
            for t in readers:
                t.start()
            _time.sleep(0.5)  # readers overlap live ingest
            mesh.kill_member(1)  # fence + rebalance under read load
            mesh.wait_idle()
            v_before = pub.store.current.version
            pub.publish_now()
            assert pub.store.current.version > v_before
        finally:
            stop.set()
            mesh.finalize()
            pub.stop()
            serve.stop()
        for t in readers:
            t.join(timeout=30)
        assert not out["errors"], out["errors"][:5]
        assert out["n"] > 50
        assert mesh.coordinator._m["rebalance"].value(
            reason="death") >= 1.0


# ---- flags -----------------------------------------------------------------


class TestInvertibleServe:
    """flowserve citizenship for -hh.sketch=invertible (r16
    acceptance): snapshots publish the decoded ranking through the
    unchanged FamilyView machinery, /query/topk stays bit-exact to the
    locked path, /query/estimate serves off the family's exact u64
    planes (no freeze conversion needed), and /query/audit works."""

    @pytest.fixture(scope="class")
    def inv_served(self):
        models = {
            "flows_5m": WindowAggregator(WindowAggConfig(batch_size=512)),
            "top_talkers": WindowedHeavyHitter(
                HeavyHitterConfig(batch_size=512, width=1 << 12,
                                  capacity=64, hh_sketch="invertible"),
                k=10),
        }
        worker = StreamWorker(
            Consumer(_fill_bus(), fixedlen=True), models, [MemorySink()],
            WorkerConfig(snapshot_every=0, poll_max=512,
                         sketch_backend="host", host_assist="on",
                         obs_audit="full"))
        pub = attach_worker(worker, refresh=0.0)
        while worker.run_once():
            pass
        with worker.lock:
            pub.publish(worker)
        serve = ServeServer(pub.store, port=0).start()
        yield worker, pub, serve
        serve.stop()

    @pytest.mark.parametrize("k", [1, 5, 10])
    def test_topk_bit_exact_vs_locked_path(self, inv_served, k):
        worker, _, serve = inv_served
        snap_ans = _get(serve.port, f"/query/topk?k={k}")
        with worker.lock:
            worker.sync_sketch_states()
            m = worker.models["top_talkers"]
            locked = rows_to_records({
                key: v[:k] for key, v in m.model.top(10).items()})
        assert snap_ans["rows"] == locked
        assert snap_ans["window_start"] == m.current_slot

    def test_estimate_serves_exact_u64_planes(self, inv_served):
        from flow_pipeline_tpu.hostsketch.engine import np_cms_query_u64

        _, pub, serve = inv_served
        fam = pub.store.current.families["top_talkers"]
        frozen = fam.cms.get()
        assert frozen.dtype == np.uint64
        lanes = np.concatenate([np.atleast_1d(fam.rows["src_addr"][0]),
                                np.atleast_1d(fam.rows["dst_addr"][0])])
        key = ",".join(str(int(x)) for x in lanes)
        est = _get(serve.port, f"/query/estimate?key={key}")
        want = np_cms_query_u64(frozen, np.asarray([lanes], np.uint32))[0]
        assert est["estimates"]["bytes"] == int(want[0])
        # decoded values are exact sums, bounded by the CMS estimate
        assert est["estimates"]["bytes"] >= int(fam.rows["bytes"][0])

    def test_query_audit_serves_invertible_reports(self, inv_served):
        _, pub, serve = inv_served
        snap = pub.store.current
        assert snap.audit, "publish carried no audit reports"
        doc = _get(serve.port, "/query/audit")
        rep = doc["models"]["top_talkers"]
        assert "cms_err" in rep and "fill_ratio" in rep
        # invertible decodes are exact: nothing is est-admitted
        assert rep["est_admitted_fraction"] == 0.0


def test_serve_flags_registered_and_parsed():
    from flow_pipeline_tpu.cli import (_common_flags, _gen_flags,
                                       _processor_flags)
    from flow_pipeline_tpu.utils.flags import KNOWN_FLAGS, FlagSet

    assert {"serve.addr", "serve.refresh"} <= KNOWN_FLAGS
    fs = _processor_flags(_gen_flags(_common_flags(FlagSet("t"))))
    vals = fs.parse(["-serve.addr", ":0", "-serve.refresh", "0.5"])
    assert vals["serve.addr"] == ":0"
    assert vals["serve.refresh"] == 0.5
