"""flowguard (guard/): the overload-control gates.

The contracts pinned here, per docs/FAULT_TOLERANCE.md "flowguard":

- **Level-0 exactness**: a disarmed guard — and an armed guard whose lag
  never leaves budget — perturbs NOTHING. Sink output is bit-exact
  against the guard-free oracle on the worker path (serial AND the
  pipelined host-grouped path, where admission runs on the group
  thread) and on the mesh path.
- **Deterministic shedding**: the shed set is a pure function of
  (flow key, level) — the same splitmix hash family as sketchwatch,
  minted from a different protocol seed. Reruns, row order, and mesh
  sharding cannot change which flows shed.
- **Unbiased estimates**: admitted survivors carry 2^shift in their
  ``sampling_rate`` column, so the scale-aware aggregates stay unbiased
  through sampled admission.
- **Exact accounting**: consumed == emitted + shed, always; every drop
  is counted on ``guard_shed_total{stage,reason}`` — nothing silent.
- **The ladder**: one transition per dwell in either direction, driven
  by watermark lag vs the ``-guard.lag`` budget, with a hysteresis band
  on recovery — no flapping, no cliff.
- **Read-side admission**: a bounded serve accept queue rejects with
  503 + Retry-After past the deadline, ``/healthz`` (admission-exempt)
  reports ``degraded``, and the flowgate ring client DEPRIORITIZES a
  degraded replica instead of declaring it dead.
- **The 2x overload soak**: a paced backlog under injected delay faults
  climbs the ladder, sheds deterministically, keeps lag bounded, serves
  zero 5xx, and recovers to level 0 when the pressure lifts.

`make guard-parity` runs this file unfiltered (slow soaks included).
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from flow_pipeline_tpu.cli import (_build_models, _common_flags,
                                   _gen_flags, _processor_flags)
from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile, ZipfProfile
from flow_pipeline_tpu.guard import (GUARD_SAMPLE_SEED, GuardConfig,
                                     GuardController, admission_mask,
                                     flow_key_lanes, register_guard_metrics)
from flow_pipeline_tpu.obs.audit import AUDIT_SAMPLE_SEED
from flow_pipeline_tpu.obs.trace import TRACER
from flow_pipeline_tpu.serve import ServeServer, SnapshotStore, attach_worker
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer
from flow_pipeline_tpu.utils.faults import FAULTS
from flow_pipeline_tpu.utils.flags import KNOWN_FLAGS, FlagSet

T0 = 1_699_999_800  # window-aligned stream start
N_FLOWS = 12_000
BATCH = 2048

# a dwell the ladder can never cross inside a test run: forced-level
# tests pin the level and must not have observe() walk it back
FROZEN = GuardConfig(lag_budget=1e6, max_level=6, hysteresis=0.5,
                     dwell=1e9)


@pytest.fixture(autouse=True)
def _clean_globals():
    yield
    FAULTS.configure(None)
    TRACER.paused = False


def _vals(*extra):
    fs = _processor_flags(_gen_flags(_common_flags(FlagSet("test"))))
    return fs.parse([
        "-produce.profile", "zipf", "-zipf.keys", "200",
        "-model.ports=false", "-model.ddos=false", "-model.ips=false",
        "-processor.batch", str(BATCH), *extra,
    ])


def _fill_bus(n_flows=N_FLOWS, seed=17, profile=None, rate=50.0):
    bus = InProcessBus()
    bus.create_topic("flows", 1)
    gen = FlowGenerator(profile or ZipfProfile(n_keys=200, alpha=1.2),
                        seed=seed, t0=T0, rate=rate)
    prod = Producer(bus, fixedlen=True)
    done = 0
    while done < n_flows:
        n = min(4096, n_flows - done)
        prod.send_many(gen.batch(n).to_messages())
        done += n
    return bus


class ListSink:
    def __init__(self):
        self.tables = {}

    def write(self, table, rows):
        self.tables.setdefault(table, []).append(rows)


def _assert_tables_bit_exact(t1: dict, t2: dict):
    assert set(t1) == set(t2)
    for table in t1:
        assert len(t1[table]) == len(t2[table]), table
        for r1, r2 in zip(t1[table], t2[table]):
            assert set(r1) == set(r2), table
            for col in r1:
                a, b = np.asarray(r1[col]), np.asarray(r2[col])
                assert a.dtype == b.dtype and a.shape == b.shape, \
                    (table, col)
                assert (a == b).all(), (table, col)


def _run_worker(bus, guard_lag=0.0, level=0, sink=None, **cfg):
    sink = ListSink() if sink is None else sink
    w = StreamWorker(
        Consumer(bus, "flows", fixedlen=True), _build_models(_vals()),
        [sink],
        WorkerConfig(poll_max=BATCH, snapshot_every=0,
                     guard_lag=guard_lag, **cfg))
    if level:
        w.guard.config = FROZEN  # never transitions inside the run
        w.guard.level = level
    w.run(stop_when_idle=True)
    return w, sink


def _get(port, path):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10).read())


# ---------------------------------------------------------------------------
# admission hash: deterministic, correctly rated, key-pure
# ---------------------------------------------------------------------------


def _key_columns(n, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "src_addr": rng.integers(0, 2**32, size=(n, 4),
                                 dtype=np.int64).astype(np.uint32),
        "dst_addr": rng.integers(0, 2**32, size=(n, 4),
                                 dtype=np.int64).astype(np.uint32),
        "src_port": rng.integers(0, 2**16, size=n,
                                 dtype=np.int64).astype(np.uint32),
        "dst_port": rng.integers(0, 2**16, size=n,
                                 dtype=np.int64).astype(np.uint32),
        "proto": rng.integers(0, 256, size=n,
                              dtype=np.int64).astype(np.uint32),
    }


class TestAdmissionHash:
    def test_mask_deterministic_and_keep_rate(self):
        cols = _key_columns(100_000)
        for shift in (1, 2, 3, 5):
            m1, m2 = admission_mask(cols, shift), admission_mask(cols, shift)
            assert (m1 == m2).all()
            keep = m1.mean()
            want = 1 / (1 << shift)
            # binomial concentration at n=100k: a generous 25% band
            assert want * 0.75 <= keep <= want * 1.25, (shift, keep)

    def test_shift_zero_admits_everything(self):
        cols = _key_columns(64)
        assert admission_mask(cols, 0).all()
        assert admission_mask(cols, -1).all()

    def test_mask_is_per_key_not_per_position(self):
        """The mesh/rerun contract: a flow sheds identically no matter
        which member, batch, or row position carries it."""
        cols = _key_columns(8192, seed=1)
        perm = np.random.default_rng(2).permutation(8192)
        permuted = {k: v[perm] for k, v in cols.items()}
        assert (admission_mask(cols, 3)[perm]
                == admission_mask(permuted, 3)).all()

    def test_levels_nest_monotonically(self):
        """Stepping the ladder down only ever SHRINKS the admitted set:
        a survivor at shift s+1 survived at shift s too (the low-bits
        hash criterion) — degradation is monotone, never a reshuffle."""
        cols = _key_columns(50_000, seed=3)
        prev = admission_mask(cols, 1)
        for shift in (2, 3, 4):
            cur = admission_mask(cols, shift)
            assert not (cur & ~prev).any(), shift
            prev = cur

    def test_uncorrelated_with_audit_cohort(self):
        """The guard seed is deliberately distinct from sketchwatch's:
        the audit cohort must keep measuring the keys that SURVIVE
        admission, not be shed first. Pin the seeds apart and the masks
        statistically independent (joint rate ~ product of rates)."""
        assert GUARD_SAMPLE_SEED != AUDIT_SAMPLE_SEED
        from flow_pipeline_tpu.obs.audit import sample_mask

        cols = _key_columns(200_000, seed=4)
        guard = admission_mask(cols, 2)  # keep 1/4
        audit = sample_mask(flow_key_lanes(cols))  # ~1/256 cohort
        joint = (guard & audit).mean()
        expect = guard.mean() * audit.mean()
        assert 0.4 * expect <= joint <= 2.5 * expect

    def test_lanes_carry_the_5_tuple(self):
        cols = _key_columns(16, seed=5)
        lanes = flow_key_lanes(cols)
        assert lanes.shape == (16, 11) and lanes.dtype == np.uint32
        assert (lanes[:, 0:4] == cols["src_addr"]).all()
        assert (lanes[:, 4:8] == cols["dst_addr"]).all()
        assert (lanes[:, 8] == cols["src_port"]).all()
        assert (lanes[:, 9] == cols["dst_port"]).all()
        assert (lanes[:, 10] == cols["proto"]).all()


# ---------------------------------------------------------------------------
# the ladder state machine (injected clock: fully deterministic)
# ---------------------------------------------------------------------------


class TestLadder:
    def _armed(self, budget=1.0, dwell=10.0, max_level=6):
        return GuardController(GuardConfig(
            lag_budget=budget, max_level=max_level, hysteresis=0.5,
            dwell=dwell))

    def test_disarmed_never_moves(self):
        g = GuardController(GuardConfig())  # lag_budget 0 = disarmed
        assert not g.armed
        for lag in (0.0, 1e9):
            assert g.observe(lag, now=100.0) == 0
        assert g.level == 0 and g.sample_shift == 0
        assert not g.drop_optional

    def test_steps_down_one_level_per_dwell(self):
        g = self._armed(budget=1.0, dwell=10.0)
        assert g.observe(5.0, now=100.0) == 1
        # inside the dwell window: pinned no matter how bad the lag
        assert g.observe(500.0, now=105.0) == 1
        assert g.observe(5.0, now=110.1) == 2
        assert g.observe(5.0, now=120.2) == 3
        assert g.m_transitions.value(direction="down") >= 3
        assert g.sample_shift == 2  # keep 1/4 at level 3
        assert g.drop_optional

    def test_ceiling_holds(self):
        g = self._armed(budget=1.0, dwell=1.0, max_level=3)
        now = 100.0
        for _ in range(10):
            g.observe(9.0, now=now)
            now += 1.1
        assert g.level == 3
        assert g.meta()["max_level_seen"] == 3

    def test_recovery_needs_the_hysteresis_band(self):
        """Under budget but above hysteresis*budget = HOLD (no
        flapping at the boundary); inside the band = step up, one
        level per dwell."""
        g = self._armed(budget=1.0, dwell=10.0)
        g.observe(5.0, now=100.0)
        g.observe(5.0, now=110.1)
        assert g.level == 2
        # 0.8 is under budget but outside the 0.5 band: held
        assert g.observe(0.8, now=130.0) == 2
        assert g.observe(0.1, now=140.0) == 1
        assert g.observe(0.1, now=145.0) == 1  # dwell gates the way UP too
        assert g.observe(0.1, now=150.1) == 0
        assert g.m_transitions.value(direction="up") >= 2

    def test_lag_gauge_tracks_observations(self):
        g = self._armed()
        g.observe(3.25, now=100.0)
        assert g.m_lag.value() == 3.25

    def test_max_level_validation(self):
        with pytest.raises(ValueError, match="max_level"):
            GuardController(GuardConfig(lag_budget=1.0, max_level=0))

    def test_worker_config_rejects_negative_budget(self):
        with pytest.raises(ValueError, match="guard_lag"):
            StreamWorker(None, {}, [], WorkerConfig(guard_lag=-0.5))


# ---------------------------------------------------------------------------
# admit(): offsets, scale factors, accounting
# ---------------------------------------------------------------------------


class TestAdmit:
    def _polled_batch(self, n=4096):
        bus = _fill_bus(n_flows=n)
        return Consumer(bus, "flows", fixedlen=True).poll(n)

    def test_level_0_and_1_admit_everything(self):
        g = GuardController(GuardConfig(lag_budget=1.0))
        batch = self._polled_batch()
        for level in (0, 1):
            g.level = level
            admitted, dropped = g.admit(batch)
            assert admitted is batch and dropped == 0

    def test_admit_keeps_offsets_scales_survivors_counts_shed(self):
        g = GuardController(GuardConfig(lag_budget=1.0))
        g.level = 3  # shift 2: keep 1/4, scale x4
        batch = self._polled_batch()
        shed0 = g.m_shed.value(stage="ingest", reason="admission")
        admitted, dropped = g.admit(batch)
        assert dropped == len(batch) - len(admitted) > 0
        # the FULL offset range survives: shed rows were consumed and
        # accounted, not lost to replay
        assert admitted.first_offset == batch.first_offset
        assert admitted.last_offset == batch.last_offset
        assert admitted.partition == batch.partition
        assert admitted.produced_at == batch.produced_at
        # survivors carry the scale (input rate 1 -> 4), exactly
        sr = admitted.columns["sampling_rate"]
        assert sr.dtype == np.uint64 and (sr == 4).all()
        # the survivor set IS the admission mask's
        mask = admission_mask(batch.columns, 2)
        assert len(admitted) == int(mask.sum())
        assert g.m_shed.value(stage="ingest",
                              reason="admission") == shed0 + dropped
        assert g.meta()["shed_total"] == dropped

    def test_absent_rate_scales_as_rate_1(self):
        g = GuardController(GuardConfig(lag_budget=1.0))
        g.level = 2  # shift 1: scale x2
        batch = self._polled_batch()
        batch.columns["sampling_rate"][:] = 0  # exporter sent none
        admitted, _ = g.admit(batch)
        assert (admitted.columns["sampling_rate"] == 2).all()

    def test_count_shed_is_never_silent(self):
        g = GuardController(GuardConfig(lag_budget=1.0))
        before = g.m_shed.value(stage="serve", reason="queue_full")
        g.count_shed(7, "serve", "queue_full")
        g.count_shed(0, "serve", "queue_full")  # no-op, not negative
        assert g.m_shed.value(stage="serve",
                              reason="queue_full") == before + 7
        assert g.meta()["shed_total"] >= 7

    def test_meta_is_json_safe(self):
        g = GuardController(GuardConfig(lag_budget=2.0))
        g.level = 4
        json.dumps(g.meta())  # must not raise
        assert g.meta()["sample_shift"] == 3
        assert g.meta()["lag_budget"] == 2.0


# ---------------------------------------------------------------------------
# level-0 bit-exactness: THE acceptance gate
# ---------------------------------------------------------------------------


class TestLevel0Parity:
    def test_disarmed_vs_armed_idle_worker_bit_exact(self):
        """An armed guard whose lag never leaves budget must not
        perturb one bit of sink output — the serial worker path."""
        _, oracle = _run_worker(_fill_bus())
        w, armed = _run_worker(_fill_bus(), guard_lag=1e6)
        assert w.guard.armed and w.guard.level == 0
        _assert_tables_bit_exact(oracle.tables, armed.tables)

    def test_disarmed_vs_armed_idle_pipelined_host_bit_exact(self):
        """The pipelined host-grouped path: admission runs inside the
        group-thread prepare wrapper — level 0 must still be exact."""
        kw = dict(sketch_backend="host", host_assist="on")
        _, oracle = _run_worker(_fill_bus(), **kw)
        w, armed = _run_worker(_fill_bus(), guard_lag=1e6, **kw)
        assert w.guard.armed and w.guard.level == 0
        _assert_tables_bit_exact(oracle.tables, armed.tables)


@pytest.mark.slow  # 2-member mesh ingest x2; gated by `make guard-parity`
class TestMeshLevel0Parity:
    def _mesh_tables(self, guard_lag):
        from flow_pipeline_tpu.engine import WindowedHeavyHitter
        from flow_pipeline_tpu.mesh import InProcessMesh, produce_sharded
        from flow_pipeline_tpu.models import (HeavyHitterConfig,
                                              WindowAggConfig,
                                              WindowAggregator)
        from flow_pipeline_tpu.sink import MemorySink

        def models():
            return {
                "flows_5m": WindowAggregator(
                    WindowAggConfig(batch_size=512)),
                "top_talkers": WindowedHeavyHitter(
                    HeavyHitterConfig(
                        key_cols=("src_addr", "dst_addr", "src_port",
                                  "dst_port", "proto"),
                        batch_size=512, width=1 << 12, capacity=128),
                    k=10),
            }

        bus = InProcessBus()
        bus.create_topic("flows", 4)
        gen = FlowGenerator(ZipfProfile(n_keys=200, alpha=1.3), seed=7,
                            t0=T0, rate=40.0)
        done = 0
        while done < 8000:
            done += produce_sharded(bus, "flows", gen.batch(2048), 4)
        sink = MemorySink()
        mesh = InProcessMesh(
            bus, "flows", 2, model_factory=models,
            config=WorkerConfig(poll_max=1024, snapshot_every=0,
                                guard_lag=guard_lag),
            sinks=[sink])
        mesh.start()
        mesh.wait_idle()
        mesh.finalize()
        return sink.tables

    def test_armed_idle_mesh_matches_disarmed_mesh(self):
        oracle = self._mesh_tables(0.0)
        armed = self._mesh_tables(1e6)
        assert set(oracle) == set(armed)
        for table in oracle:
            assert sorted(map(repr, oracle[table])) \
                == sorted(map(repr, armed[table])), table


# ---------------------------------------------------------------------------
# sampled admission: deterministic shed set, unbiased scaled estimates
# ---------------------------------------------------------------------------


class TestSampledAdmission:
    def test_shed_set_reproduces_across_reruns(self):
        """Two forced-level runs over identical streams shed the SAME
        flows: sink output bit-exact, counters equal."""
        w1, s1 = _run_worker(_fill_bus(), guard_lag=1e6, level=3)
        w2, s2 = _run_worker(_fill_bus(), guard_lag=1e6, level=3)
        assert w1.guard.meta()["shed_total"] > 0
        assert w1.flows_seen == w2.flows_seen
        assert w1.guard.meta()["shed_total"] == w2.guard.meta()["shed_total"]
        _assert_tables_bit_exact(s1.tables, s2.tables)

    def test_accounting_identity_and_unbiased_scaling(self):
        """consumed == emitted + shed, exactly; and the scale-aware
        aggregate (`bytes_scaled`) stays an unbiased estimate of the
        guard-free total through keep-rate-1/4 admission."""
        n = 16_384
        profile = MockerProfile()  # flat key mass: tight concentration
        _, oracle = _run_worker(_fill_bus(n_flows=n, profile=profile))
        # the counter is registry-global: assert the run's delta
        c0 = register_guard_metrics()["shed"].value(stage="ingest",
                                                    reason="admission")
        w, armed = _run_worker(_fill_bus(n_flows=n, profile=profile),
                               guard_lag=1e6, level=3)
        shed = w.guard.meta()["shed_total"]
        assert shed > 0
        assert w.flows_seen + shed == n  # exact accounting
        assert w.guard.m_shed.value(stage="ingest",
                                    reason="admission") == c0 + shed
        # keep rate ~1/4 at level 3
        assert 0.15 <= w.flows_seen / n <= 0.40

        def totals(sink, col):
            return sum(int(np.asarray(rows[col]).sum())
                       for rows in sink.tables["flows_5m"])

        exact = totals(oracle, "bytes")
        assert totals(oracle, "bytes_scaled") == exact  # rate-1 input
        scaled = totals(armed, "bytes_scaled")
        raw = totals(armed, "bytes")
        assert raw < exact  # 3/4 of the mass was shed...
        assert abs(scaled - exact) / exact < 0.15  # ...and scaled back

    def test_level_1_pauses_optional_work_sheds_nothing(self):
        """Level 1 is loud but lossless: the trace ring pauses, yet
        every flow still lands — shed_total stays 0 and the accounting
        shows no loss."""
        w, _ = _run_worker(_fill_bus(n_flows=4096), guard_lag=1e6,
                           level=1)
        assert TRACER.paused  # optional work went quiet
        assert w.flows_seen == 4096
        assert w.guard.meta()["shed_total"] == 0
        # and a level-0 run leaves the instruments running
        w2, _ = _run_worker(_fill_bus(n_flows=4096), guard_lag=1e6)
        assert not TRACER.paused
        assert w2.flows_seen == 4096


# ---------------------------------------------------------------------------
# bounded buffers: the byte gauges exist and drain
# ---------------------------------------------------------------------------


def test_buffer_byte_gauges_register_and_drain():
    """guard_buffer_bytes{stage} tracks the two bounded ingest
    handoffs (feed prefetch, prepared-batch queue) and reads 0 once
    the pipeline drains — bounded by construction, observable live."""
    w, _ = _run_worker(_fill_bus(), sketch_backend="host",
                       host_assist="on")
    assert w.executor is not None  # the pipelined path actually ran
    g = register_guard_metrics()["buffer_bytes"]
    assert g.value(stage="group") == 0
    assert g.value(stage="feed") == 0
    with g._lock:
        stages = {dict(k).get("stage") for k in g._values}
    assert {"feed", "group"} <= stages


# ---------------------------------------------------------------------------
# snapshot metadata: readers can tell what level built their answer
# ---------------------------------------------------------------------------


class TestSnapshotMetadata:
    def test_armed_guard_meta_rides_the_snapshot_and_audit_endpoint(self):
        bus = _fill_bus(n_flows=4096)
        w = StreamWorker(
            Consumer(bus, "flows", fixedlen=True),
            _build_models(_vals()), [ListSink()],
            WorkerConfig(poll_max=BATCH, snapshot_every=0,
                         guard_lag=1e6))
        pub = attach_worker(w, refresh=0.0)
        w.run(stop_when_idle=True)
        with w.lock:
            snap = pub.publish(w)
        meta = snap.audit["flowguard"]
        assert meta["level"] == 0 and meta["lag_budget"] == 1e6
        serve = ServeServer(pub.store, port=0).start()
        try:
            doc = _get(serve.port, "/query/audit")
            assert doc["models"]["flowguard"]["level"] == 0
        finally:
            serve.stop()

    def test_disarmed_guard_stays_out_of_the_snapshot(self):
        bus = _fill_bus(n_flows=4096)
        w = StreamWorker(
            Consumer(bus, "flows", fixedlen=True),
            _build_models(_vals()), [ListSink()],
            WorkerConfig(poll_max=BATCH, snapshot_every=0))
        pub = attach_worker(w, refresh=0.0)
        w.run(stop_when_idle=True)
        with w.lock:
            snap = pub.publish(w)
        assert "flowguard" not in snap.audit


# ---------------------------------------------------------------------------
# read-side admission: bounded accept queue, honest 503, live /healthz
# ---------------------------------------------------------------------------


def _mk_state(version, bump=0):
    """Minimal canonical state (one dense family, one range table) so
    the serve/gateway paths have real bodies to answer with."""
    return {
        "version": int(version), "created": 100.0 + version,
        "watermark": float(T0 + 300 * version), "flows_seen": 10 * version,
        "source": "worker",
        "families": {
            "dense": {"kind": "dense", "window_start": T0, "depth": 4,
                      "key_lanes": 1, "value_cols": [],
                      "rows": {"port": np.arange(4, dtype=np.uint32)
                               + np.uint32(bump)},
                      "cms": None},
        },
        "ranges": {"flows_5m": [
            [T0, {"timeslot": np.asarray([T0], np.int64),
                  "bytes": np.asarray([bump + 1], np.uint64)}],
        ]},
        "audit": {},
    }


def _store_at(versions, bump=0):
    from flow_pipeline_tpu.gateway import state_to_snapshot

    store = SnapshotStore()
    for v in versions:
        store.publish_snapshot(state_to_snapshot(_mk_state(v, bump=bump + v)))
    return store


class TestServeAdmission:
    def test_queue_full_rejects_loudly_healthz_exempt(self):
        store = _store_at([1])
        serve = ServeServer(store, port=0, max_inflight=1,
                            deadline=0.01).start()
        g = register_guard_metrics()["shed"]
        shed0 = g.value(stage="serve", reason="queue_full")
        e5xx0 = store.m_responses.value(code="503")
        try:
            assert _get(serve.port, "/query/topk")["model"] == "dense"
            assert serve._sem.acquire(timeout=1)  # saturate the queue
            try:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{serve.port}/query/topk",
                        timeout=10)
                assert ei.value.code == 503
                assert ei.value.headers["Retry-After"] == "1"
                assert b"overloaded" in ei.value.read()
                # liveness stays observable under exactly the overload
                # that saturates the query paths
                assert _get(serve.port, "/healthz")["ok"] is True
            finally:
                serve._sem.release()
            # the shed was counted AND attributed; pressure off -> 200s
            assert g.value(stage="serve",
                           reason="queue_full") == shed0 + 1
            assert store.m_responses.value(code="503") == e5xx0 + 1
            assert _get(serve.port, "/query/topk")["model"] == "dense"
        finally:
            serve.stop()

    def test_negative_deadline_rejected(self):
        with pytest.raises(ValueError, match="deadline"):
            ServeServer(SnapshotStore(), port=0, max_inflight=1,
                        deadline=-0.1)

    def test_healthz_reports_degraded_with_guard_level(self):
        store = _store_at([1])
        guard = GuardController(GuardConfig(lag_budget=1.0))
        serve = ServeServer(store, port=0).set_guard(guard).start()
        try:
            h = _get(serve.port, "/healthz")
            assert h["degraded"] is False and "guard_level" not in h
            guard.level = 2
            h = _get(serve.port, "/healthz")
            assert h["degraded"] is True and h["guard_level"] == 2
        finally:
            serve.stop()


class TestRingDeprioritizesDegraded:
    def test_503_reroutes_without_declaring_dead(self):
        """A replica answering 503 + Retry-After is DEGRADED: the ring
        client reroutes to another arc (zero surfaced errors) and only
        when EVERY replica sheds does the honest 503 surface."""
        from flow_pipeline_tpu.gateway import GatewayClient

        deg = ServeServer(_store_at([1]), port=0, max_inflight=1,
                          deadline=0.01).start()
        ok = ServeServer(_store_at([1]), port=0, max_inflight=1,
                         deadline=0.01).start()
        try:
            deg_node = f"127.0.0.1:{deg.port}"
            client = GatewayClient([deg_node, f"127.0.0.1:{ok.port}"])
            path = next(p for p in (f"/query/topk?k={i}"
                                    for i in range(300))
                        if client.ring.node_for(p) == deg_node)
            assert deg._sem.acquire(timeout=1)  # saturate the one arc
            try:
                code, body = client.get(path)
                assert code == 200 and b"dense" in body
                assert client.deprioritized >= 1
                assert client.retries == 0  # degraded, NOT dead
                # every arc overloaded: the shed is surfaced honestly,
                # retryable — never a transport error
                assert ok._sem.acquire(timeout=1)
                try:
                    code, body = client.get(path)
                    assert code == 503 and b"overloaded" in body
                finally:
                    ok._sem.release()
            finally:
                deg._sem.release()
        finally:
            deg.stop()
            ok.stop()


# ---------------------------------------------------------------------------
# -gateway.adopt-restart: both restart stances (the r20 satellite)
# ---------------------------------------------------------------------------


class TestAdoptRestart:
    def _wired_gateway(self, **kw):
        from flow_pipeline_tpu.gateway import SnapshotGateway

        up_store = _store_at([1, 2, 3])
        gw = SnapshotGateway([up_store], poll=60, **kw)
        srv = ServeServer(gw.store, port=0).start()
        gw.serve_on(srv)
        assert gw.sync_once() == "full"
        assert gw.store.current.version == 3
        return gw, srv

    def _restart_upstream(self, gw, versions, bump):
        from flow_pipeline_tpu.gateway import SnapshotFeed

        fresh = _store_at(versions, bump=bump)
        gw.upstreams[0]._feed = SnapshotFeed(fresh)
        return fresh

    def test_default_keeps_pre_restart_snapshot(self):
        """The monotone default: the restart is counted (the alert's
        signal) but never adopted — readers keep the old world until an
        operator restarts the replica."""
        gw, srv = self._wired_gateway()
        old = _get(srv.port, "/query/topk")["rows"]
        up = gw.upstreams[0]
        r0 = gw._m["upstream_restarts"].value(upstream=up.name)
        self._restart_upstream(gw, [1], bump=100)
        try:
            assert gw.sync_once() == "full"
            assert gw.store.current.version == 3  # never adopted
            assert gw._m["upstream_restarts"].value(
                upstream=up.name) == r0 + 1
            assert _get(srv.port, "/query/topk")["rows"] == old
        finally:
            srv.stop()

    def test_adopt_restart_swaps_worlds_and_flushes_the_cache(self):
        """-gateway.adopt-restart: availability wins. The full frame is
        adopted, the restart is STILL counted (never silent), and the
        response cache is flushed — when the post-restart stream later
        reaches v3 again, its version number COLLIDES with the old
        world's cached v3 body, which the version-equality cache check
        alone cannot tell apart."""
        from flow_pipeline_tpu.gateway import state_to_snapshot

        gw, srv = self._wired_gateway(adopt_restart=True)
        old_rows = _get(srv.port, "/query/topk")["rows"]  # cache primed
        up = gw.upstreams[0]
        r0 = gw._m["upstream_restarts"].value(upstream=up.name)
        fresh = self._restart_upstream(gw, [1], bump=100)
        try:
            assert gw.sync_once() == "full"
            # adopted: the replica jumped BACKWARD to the new world
            assert gw.store.current.version == 1
            assert gw._m["upstream_restarts"].value(
                upstream=up.name) == r0 + 1
            assert _get(srv.port, "/query/topk")["rows"][0]["port"] == 101
            # the post-restart stream flows normally (deltas) and walks
            # back up to the colliding version number
            for v in (2, 3):
                fresh.publish_snapshot(
                    state_to_snapshot(_mk_state(v, bump=100 + v)))
            assert gw.sync_once() == "delta"
            assert gw.store.current.version == 3
            new_rows = _get(srv.port, "/query/topk")["rows"]
            assert new_rows != old_rows  # NOT the stale cached v3 body
            assert new_rows[0]["port"] == 103
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# the 2x overload soak (slow): bounded lag, exact accounting, zero 5xx
# ---------------------------------------------------------------------------


@pytest.mark.slow  # multi-second backlog soak; gated by `make guard-parity`
class TestOverloadSoak:
    def test_backlog_under_injected_delay_sheds_recovers_exactly(self):
        """A prefilled backlog consumed under injected poll-delay
        faults drives lag past a tight budget: the ladder climbs to
        sampling levels, admission sheds deterministically, lag stays
        bounded, the serve surface answers zero 5xx with /healthz
        flipping degraded, and the accounting closes exactly —
        consumed == emitted + shed. When the backlog drains, idle
        observations walk the ladder back to level 0."""
        n = 60_000
        bus = _fill_bus(n_flows=n, rate=200.0)
        sink = ListSink()
        w = StreamWorker(
            Consumer(bus, "flows", fixedlen=True),
            _build_models(_vals()), [sink],
            WorkerConfig(poll_max=BATCH, snapshot_every=0, prefetch=0,
                         guard_lag=0.25))
        # bench-cadence ladder: the production 5 s dwell cannot climb
        # inside a seconds-long soak
        w.guard.config = GuardConfig(lag_budget=0.25, max_level=6,
                                     hysteresis=0.5, dwell=0.1)
        pub = attach_worker(w, refresh=0.0)
        with w.lock:
            pub.publish(w)
        serve = ServeServer(pub.store, port=0).set_guard(w.guard).start()
        c0 = register_guard_metrics()["shed"].value(stage="ingest",
                                                    reason="admission")
        # the responses counter is registry-global: snapshot the 5xx
        # families now and assert the SOAK added none
        def _5xx_total():
            with pub.store.m_responses._lock:
                return sum(v for k, v in
                           pub.store.m_responses._values.items()
                           if dict(k).get("code", "").startswith("5"))
        e0 = _5xx_total()
        FAULTS.configure("bus.poll:delay=0.02@seed=5")
        max_lag = 0.0
        degraded_seen = False
        try:
            while w.run_once():
                max_lag = max(max_lag, w.guard.m_lag.value())
                if w.batches_seen % 4 == 0:
                    h = _get(serve.port, "/healthz")
                    degraded_seen |= h["degraded"]
                    assert _get(serve.port,
                                "/query/version")["version"] >= 1
            w.finalize()
        finally:
            FAULTS.configure(None)
        meta = w.guard.meta()
        # the ladder engaged past the pause level into sampling
        assert meta["max_level_seen"] >= 2
        assert degraded_seen
        # exact shed accounting: every consumed flow is emitted or
        # counted shed, nothing silent, nothing double-counted
        assert meta["shed_total"] > 0
        assert w.flows_seen + meta["shed_total"] == n
        assert w.guard.m_shed.value(
            stage="ingest", reason="admission") == c0 + meta["shed_total"]
        # lag stayed bounded (the backlog is finite and shedding bites)
        assert max_lag < 30.0
        # zero serve 5xx through the whole soak
        assert _5xx_total() == e0
        # pressure off: idle observations recover to exact, with the
        # dwell pacing each step up
        deadline = time.monotonic() + 30
        while w.guard.level > 0 and time.monotonic() < deadline:
            w.guard.observe(0.0)
            time.sleep(0.02)
        assert w.guard.level == 0
        h = _get(serve.port, "/healthz")
        assert h["degraded"] is False
        serve.stop()


# ---------------------------------------------------------------------------
# flags / wiring
# ---------------------------------------------------------------------------


def test_guard_flags_registered_and_parsed():
    assert {"guard.lag", "guard.max_level", "guard.serve_queue",
            "guard.serve_deadline",
            "gateway.adopt-restart"} <= KNOWN_FLAGS
    fs = FlagSet("t")
    fs.number("guard.lag", 0.0, "h")
    fs.integer("guard.max_level", 6, "h")
    fs.integer("guard.serve_queue", 0, "h")
    fs.number("guard.serve_deadline", 0.1, "h")
    fs.boolean("gateway.adopt-restart", False, "h")
    vals = fs.parse(["-guard.lag", "2.5", "-guard.max_level", "4",
                     "-guard.serve_queue", "64",
                     "-gateway.adopt-restart"])
    assert vals["guard.lag"] == 2.5
    assert vals["guard.max_level"] == 4
    assert vals["guard.serve_queue"] == 64
    assert vals["guard.serve_deadline"] == 0.1
    assert vals["gateway.adopt-restart"] is True


def test_faults_delay_clause_sleeps_and_counts():
    """The r20 `-faults` delay grammar: a delay-only clause hits with
    p=1, SLEEPS instead of raising, and is counted per site on
    faults_delayed_total — the overload soak's stall injector."""
    FAULTS.configure("bus.poll:delay=0.01;sink.write:p=0@seed=3")
    try:
        t0 = time.perf_counter()
        FAULTS.check("bus.poll")  # must not raise
        assert time.perf_counter() - t0 >= 0.008
        FAULTS.check("sink.write")  # p=0: never fires
        snap = FAULTS.snapshot()
        assert snap["bus.poll"]["delayed"] == 1
        assert snap["bus.poll"]["p"] == 1.0  # delay-only implies p=1
        assert snap["sink.write"]["delayed"] == 0
    finally:
        FAULTS.configure(None)
