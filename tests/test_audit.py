"""sketchwatch (obs/audit.py): sampling determinism, the uint64-exact
cohort envelope vs the exact_groupby oracle past 2^53, audit-on vs
audit-off sink-row bit-exactness (single worker AND the 4-worker mesh
churn leg), mesh-merged audit counters bit-equal to the single-worker
oracle's cohort, the /query/audit serve surface, and the
Histogram.remove() / coordinator series-lifecycle regressions.
`make audit-parity` runs this file."""

import json
import time

import numpy as np
import pytest

from flow_pipeline_tpu.cli import (_build_models, _common_flags,
                                   _gen_flags, _processor_flags)
from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.mesh import InProcessMesh, produce_sharded
from flow_pipeline_tpu.mesh import merge as merge_ops
from flow_pipeline_tpu.models.heavy_hitter import HeavyHitterConfig
from flow_pipeline_tpu.obs.audit import (AUDIT_SAMPLE_BITS, SketchAudit,
                                         audit_report, sample_mask)
from flow_pipeline_tpu.schema.batch import FlowBatch
from flow_pipeline_tpu.transport import Consumer, InProcessBus
from flow_pipeline_tpu.utils.flags import KNOWN_FLAGS, FlagSet

N_KEYS = 200  # << capacity: admission is collision-free, tables exact
N_FLOWS = 24_000
PARTITIONS = 8
BATCH = 4096

TOP_COLS = ("src_addr", "dst_addr", "src_port", "dst_port", "proto",
            "bytes", "packets", "count", "timeslot")


def _vals(*extra):
    # identical knobs to tests/test_mesh.py so the jitted apply graphs
    # are shared across the pytest process (the suite must stay fast)
    fs = _processor_flags(_gen_flags(_common_flags(FlagSet("test"))))
    return fs.parse([
        "-produce.profile", "zipf", "-zipf.keys", str(N_KEYS),
        "-model.ports=false", "-model.ddos=false", "-model.ips=false",
        "-processor.batch", str(BATCH), "-sketch.capacity", "512",
        *extra,
    ])


def _stream_batches(n_flows=N_FLOWS, seed=0):
    gen = FlowGenerator(ZipfProfile(n_keys=N_KEYS, alpha=1.2), seed=seed,
                        rate=100_000.0)
    out, done = [], 0
    while done < n_flows:
        n = min(8192, n_flows - done)
        out.append(gen.batch(n))
        done += n
    return out


def _make_bus(n_flows=N_FLOWS, partitions=PARTITIONS):
    bus = InProcessBus()
    bus.create_topic("flows", partitions)
    for batch in _stream_batches(n_flows):
        produce_sharded(bus, "flows", batch, partitions)
    return bus


class ListSink:
    def __init__(self):
        self.tables = {}

    def write(self, table, rows):
        self.tables.setdefault(table, []).append(rows)


def _run_worker(vals, sink, audit="off", backend=None):
    worker = StreamWorker(
        Consumer(_make_bus(), "flows", fixedlen=True),
        _build_models(vals), [sink],
        WorkerConfig(poll_max=BATCH, snapshot_every=0,
                     sketch_backend=backend or vals["sketch.backend"],
                     obs_audit=audit))
    worker.run(stop_when_idle=True)
    return worker


def _assert_tables_bit_exact(t1: dict, t2: dict):
    assert set(t1) == set(t2)
    for table in t1:
        assert len(t1[table]) == len(t2[table]), table
        for r1, r2 in zip(t1[table], t2[table]):
            assert set(r1) == set(r2), table
            for col in r1:
                a, b = np.asarray(r1[col]), np.asarray(r2[col])
                assert a.dtype == b.dtype and a.shape == b.shape, \
                    (table, col)
                assert (a == b).all(), (table, col)


# ---------------------------------------------------------------------------
# deterministic sampling
# ---------------------------------------------------------------------------


class TestSampling:
    def test_mask_deterministic_and_roughly_1_in_256(self):
        rng = np.random.default_rng(0)
        lanes = rng.integers(0, 2**32, size=(100_000, 5),
                             dtype=np.int64).astype(np.uint32)
        m1, m2 = sample_mask(lanes), sample_mask(lanes)
        assert (m1 == m2).all()
        # binomial(100k, 1/256): mean ~390, assert a generous band
        assert 150 <= int(m1.sum()) <= 800

    def test_mask_is_per_key_not_per_position(self):
        """The mesh contract: a key samples identically regardless of
        which shard/chunk/row position carries it."""
        rng = np.random.default_rng(1)
        lanes = rng.integers(0, 2**32, size=(4096, 2),
                             dtype=np.int64).astype(np.uint32)
        perm = rng.permutation(len(lanes))
        assert (sample_mask(lanes)[perm] == sample_mask(lanes[perm])).all()

    def test_full_mode_audits_everything(self):
        lanes = np.zeros((7, 3), np.uint32)
        assert sample_mask(lanes, "full").all()


# ---------------------------------------------------------------------------
# uint64-exact envelope: cohort sums vs the exact oracle past 2^53
# ---------------------------------------------------------------------------


class TestUint64Envelope:
    def test_cohort_sums_match_exact_groupby_past_2_53(self):
        """Per-key cohort totals above 2^53 (where float64 accumulation
        already rounds) must bit-equal the uint64 exact_groupby oracle:
        the audit's fold is u64 addition of f32-exact addends."""
        from flow_pipeline_tpu.models.oracle import exact_groupby

        n = 16_384
        rng = np.random.default_rng(2)
        src = (rng.integers(0, 4, size=n) + 10).astype(np.uint32)
        dst = np.full(n, 77, np.uint32)
        # 2^42 per row is exactly representable in f32; a key's total
        # crosses 2^53 after ~2k rows (each key gets ~4k here)
        bytes_col = np.full(n, np.uint64(1) << np.uint64(42), np.uint64)
        batch = FlowBatch({
            "time_received": np.full(n, 1_000, np.uint32),
            "src_as": src, "dst_as": dst,
            "bytes": bytes_col,
            "packets": np.ones(n, np.uint64),
        })
        oracle = exact_groupby(batch, ["src_as", "dst_as"],
                               ["bytes", "packets"], timeslot=False)
        assert int(oracle["bytes"].max()) > 2**53  # the test has teeth
        cfg = HeavyHitterConfig(key_cols=("src_as", "dst_as"),
                                value_cols=("bytes", "packets"),
                                batch_size=n, scale_col=None)
        audit = SketchAudit({"env": (cfg, 10)}, mode="full")
        # feed per-row (the fused path's shape), in two chunks
        lanes = np.stack([src, dst], axis=1).astype(np.uint32)
        vals = np.stack([bytes_col, np.ones(n, np.uint64)],
                        axis=1).astype(np.float32)
        audit.observe_rows("env", lanes[:n // 2], vals[:n // 2])
        audit.observe_rows("env", lanes[n // 2:], vals[n // 2:])
        part = audit.take_partial("env")
        got = {tuple(int(x) for x in part["keys"][i]):
               part["vals"][i] for i in range(len(part["keys"]))}
        assert len(got) == len(oracle["src_as"])
        for i in range(len(oracle["src_as"])):
            key = (int(oracle["src_as"][i]), int(oracle["dst_as"][i]))
            want = np.array([oracle["bytes"][i], oracle["packets"][i],
                             oracle["count"][i]], np.uint64)
            assert (got[key] == want).all(), key

    def test_grouped_and_row_observation_agree_on_envelope(self):
        """Chunk grouping granularity must not change the cohort: group
        sums (staged path) and per-row addends (fused path) fold to the
        same uint64 totals on the exact envelope."""
        n = 4096
        rng = np.random.default_rng(3)
        lanes = (rng.integers(0, 50, size=(n, 2))).astype(np.uint32)
        vals = rng.integers(1, 1500, size=(n, 2)).astype(np.float32)
        cfg = HeavyHitterConfig(key_cols=("src_as", "dst_as"),
                                value_cols=("bytes", "packets"),
                                batch_size=n, scale_col=None)
        a_rows = SketchAudit({"f": (cfg, 10)}, mode="full")
        a_rows.observe_rows("f", lanes, vals)
        a_grp = SketchAudit({"f": (cfg, 10)}, mode="full")
        order = np.lexsort(lanes.T[::-1])
        sk = lanes[order]
        bound = np.ones(n, bool)
        bound[1:] = (sk[1:] != sk[:-1]).any(axis=1)
        starts = np.flatnonzero(bound)
        uniq = np.ascontiguousarray(sk[starts])
        vsum = np.add.reduceat(vals[order].astype(np.float64), starts,
                               axis=0).astype(np.float32)
        cnt = np.diff(np.append(starts, n)).astype(np.float32)
        sums = np.concatenate([vsum, cnt[:, None]], axis=1)
        a_grp.observe_grouped("f", uniq, sums, len(uniq))
        p1, p2 = a_rows.take_partial("f"), a_grp.take_partial("f")
        assert (p1["keys"] == p2["keys"]).all()
        assert (p1["vals"] == p2["vals"]).all()


# ---------------------------------------------------------------------------
# report semantics
# ---------------------------------------------------------------------------


class TestReport:
    @staticmethod
    def _state(cms, keys, vals):
        return {"cms": cms, "table_keys": keys, "table_vals": vals}

    def test_exact_regime_reports_zero_and_full_recall(self):
        """A sketch wide enough that the cohort's estimates are exact
        must report 0 error, recall 1, no false drops."""
        from flow_pipeline_tpu.hostsketch.engine import np_cms_update

        cfg = HeavyHitterConfig(key_cols=("src_as", "dst_as"),
                                value_cols=("bytes", "packets"),
                                width=1 << 16, capacity=16,
                                batch_size=64, scale_col=None)
        keys = np.arange(20, dtype=np.uint32).reshape(10, 2)
        counts = np.arange(10, 0, -1).astype(np.uint64)
        cms = np.zeros((3, cfg.depth, cfg.width), np.uint64)
        vals = np.stack([counts * 100, counts, counts],
                        axis=1).astype(np.float32)
        np_cms_update(cms, keys, vals, conservative=True)
        tkeys = np.full((16, 2), 0xFFFFFFFF, np.uint32)
        tvals = np.zeros((16, 3), np.float32)
        tkeys[:10] = keys
        tvals[:10] = vals
        cohort = np.stack([counts * 100, counts, counts],
                          axis=1).astype(np.uint64)
        rep = audit_report(keys, cohort, self._state(cms, tkeys, tvals),
                           cfg, k=5, scale=1)
        assert rep["cms_err"] == {"p50": 0.0, "p99": 0.0, "max": 0.0}
        assert rep["table_err"] == {"p50": 0.0, "p99": 0.0, "max": 0.0}
        assert rep["recall_at_k"] == 1.0
        assert rep["precision_at_k"] == 1.0
        assert rep["false_drops"] == 0
        assert rep["sampled_keys"] == 10
        assert rep["table_occupancy"] == pytest.approx(10 / 16)

    def test_missing_heavy_key_counts_as_false_drop(self):
        cfg = HeavyHitterConfig(key_cols=("src_as", "dst_as"),
                                value_cols=("bytes", "packets"),
                                width=1 << 10, capacity=4,
                                batch_size=64, scale_col=None)
        keys = np.arange(8, dtype=np.uint32).reshape(4, 2)
        cohort = np.stack([[400, 300, 200, 100]] * 3,
                          axis=1).astype(np.uint64)
        cms = np.zeros((3, cfg.depth, cfg.width), np.uint64)
        tkeys = np.full((4, 2), 0xFFFFFFFF, np.uint32)
        tvals = np.zeros((4, 3), np.float32)
        tkeys[0] = keys[1]  # the TOP key (row 0) is missing entirely
        tvals[0] = [300, 300, 300]
        rep = audit_report(keys, cohort, self._state(cms, tkeys, tvals),
                           cfg, k=2, scale=1)
        assert rep["false_drops"] >= 1
        assert rep["recall_at_k"] < 1.0

    def test_error_grows_with_fill(self):
        """The acceptance direction: the same stream through a narrow
        sketch reports strictly more error than through a wide one, and
        the wide (exact-regime) sketch reports zero."""
        from flow_pipeline_tpu.hostsketch.engine import np_cms_update

        rng = np.random.default_rng(5)
        keys = rng.integers(0, 2**32, size=(2000, 2),
                            dtype=np.int64).astype(np.uint32)
        keys = np.unique(keys, axis=0)
        n = len(keys)
        counts = rng.integers(1, 100, size=n).astype(np.uint64)
        vals = np.stack([counts, counts, counts],
                        axis=1).astype(np.float32)
        cohort = vals.astype(np.uint64)
        errs = {}
        for width in (1 << 16, 1 << 7):
            cfg = HeavyHitterConfig(key_cols=("src_as", "dst_as"),
                                    value_cols=("bytes", "packets"),
                                    width=width, capacity=16,
                                    batch_size=64, scale_col=None)
            cms = np.zeros((3, cfg.depth, width), np.uint64)
            np_cms_update(cms, keys, vals, conservative=True)
            tkeys = np.full((16, 2), 0xFFFFFFFF, np.uint32)
            tvals = np.zeros((16, 3), np.float32)
            rep = audit_report(keys, cohort,
                               self._state(cms, tkeys, tvals),
                               cfg, k=16, scale=1)
            errs[width] = (rep["cms_err"]["p99"],
                           rep["fill_ratio"][-1])
        assert errs[1 << 16][0] == 0.0  # exact regime reports 0
        assert errs[1 << 7][1] > errs[1 << 16][1]  # fill grew...
        assert errs[1 << 7][0] > 0.0               # ...and so did error


class TestInvertibleAudit:
    """sketchwatch gate for -hh.sketch=invertible (r16): the audit is
    backend-agnostic — the invertible family's decoded ranking audits
    through the same report machinery, reports the exact regime as
    error 0 (every observation in the le="0" bucket), and its
    recall@k on the error-vs-fill sweep never falls below table mode
    (decoded values are exact; admission loss does not exist)."""

    @staticmethod
    def _sweep_stream(seed=17, n_keys=3000, rows=12000):
        rng = np.random.default_rng(seed)
        ids = (rng.zipf(1.3, size=rows) % n_keys).astype(np.uint32)
        keys = np.stack([ids * np.uint32(2654435761),
                         ids ^ np.uint32(0x9E3779B9)], axis=1)
        vals = rng.integers(1, 1500, size=rows).astype(np.float32)
        return keys, vals

    @classmethod
    def _grouped(cls, keys, vals):
        order = np.lexsort(keys.T[::-1])
        sk = keys[order]
        bound = np.ones(len(sk), bool)
        bound[1:] = (sk[1:] != sk[:-1]).any(axis=1)
        starts = np.flatnonzero(bound)
        uniq = np.ascontiguousarray(sk[starts])
        vsum = np.add.reduceat(vals[order].astype(np.float64),
                               starts).astype(np.float32)
        cnt = np.diff(np.append(starts, len(sk))).astype(np.float32)
        return uniq, np.stack([vsum, vsum, cnt], axis=1)

    def _audit_point(self, hh_sketch, width, keys, vals):
        from flow_pipeline_tpu.hostsketch.engine import HostSketchEngine
        from flow_pipeline_tpu.obs.audit import SketchAudit

        cfg = HeavyHitterConfig(
            key_cols=("src_as", "dst_as"), width=width, capacity=256,
            batch_size=4096, scale_col=None, hh_sketch=hh_sketch)
        engine = HostSketchEngine([cfg], use_native="auto")
        engine.reset(0)
        audit = SketchAudit({"fam": (cfg, 64)}, mode="full")
        uniq, sums = self._grouped(keys, vals)
        engine.update(0, uniq, sums, len(uniq))
        audit.observe_grouped("fam", uniq, sums, len(uniq))
        part = audit.take_partial("fam")
        return audit_report(part["keys"], part["vals"],
                            engine.states[0], cfg, 64, scale=1)

    def test_exact_regime_reports_zero_error(self):
        """Wide sketch, keys << buckets: the invertible decode is exact
        and BOTH error paths report 0 — the le="0" acceptance signal."""
        keys, vals = self._sweep_stream(n_keys=400, rows=6000)
        rep = self._audit_point("invertible", 1 << 16, keys, vals)
        assert rep["cms_err"] == {"p50": 0.0, "p99": 0.0, "max": 0.0}
        assert rep["table_err"] == {"p50": 0.0, "p99": 0.0, "max": 0.0}
        assert rep["recall_at_k"] == 1.0
        assert rep["false_drops"] == 0
        # decoded values are exact, never CMS-seeded upper bounds
        assert rep["est_admitted_fraction"] == 0.0

    def test_exact_regime_observations_land_in_le0_bucket(self):
        """The rendered histogram carries the signal dashboards gate
        on: every exact-regime observation cumulates into le="0"."""
        from flow_pipeline_tpu.obs import REGISTRY
        from flow_pipeline_tpu.obs.audit import publish_report

        keys, vals = self._sweep_stream(seed=23, n_keys=300, rows=5000)
        rep = self._audit_point("invertible", 1 << 16, keys, vals)
        fam = "inv_le0_gate"
        publish_report(fam, rep)
        hist = REGISTRY._metrics["sketch_estimate_error_ratio"]
        rendered = hist.render()
        for path in ("cms", "table"):
            le0 = total = None
            for line in rendered.splitlines():
                if f'family="{fam}"' not in line or f'path="{path}"' \
                        not in line:
                    continue
                if 'le="0"' in line:
                    le0 = float(line.rsplit(" ", 1)[1])
                elif line.startswith(
                        "sketch_estimate_error_ratio_count"):
                    total = float(line.rsplit(" ", 1)[1])
            assert le0 is not None and total is not None and total > 0
            assert le0 == total, (path, le0, total)

    def test_recall_at_least_table_mode_on_fill_sweep(self):
        """The same stream through both families at shrinking widths:
        invertible recall@k must never fall below table mode's (and
        both report exact at the widest point)."""
        keys, vals = self._sweep_stream()
        for width in (1 << 16, 1 << 12, 1 << 9):
            rep_inv = self._audit_point("invertible", width, keys, vals)
            rep_tab = self._audit_point("table", width, keys, vals)
            assert rep_inv["recall_at_k"] is not None
            assert rep_inv["recall_at_k"] >= rep_tab["recall_at_k"], \
                (width, rep_inv["recall_at_k"], rep_tab["recall_at_k"])


# ---------------------------------------------------------------------------
# audit-parity: instrumentation must be purely observational
# ---------------------------------------------------------------------------


class TestAuditParity:
    def test_worker_sink_rows_bit_exact_audit_on_off(self):
        """The acceptance gate, worker leg: -obs.audit=off vs full on
        the fused host dataplane — every sink row bit-exact."""
        vals = _vals("-sketch.backend", "host")
        s_off, s_on = ListSink(), ListSink()
        w_off = _run_worker(vals, s_off, audit="off")
        w_on = _run_worker(vals, s_on, audit="full")
        assert getattr(w_off.fused, "audit", None) is None
        assert w_on.fused.audit is not None
        assert w_on.fused.audit.last_reports  # it DID audit something
        _assert_tables_bit_exact(s_off.tables, s_on.tables)

    def test_mesh_churn_sink_rows_bit_exact_audit_on_off(self):
        """The acceptance gate, mesh leg: a 4-worker mesh with a
        mid-stream member kill stays bit-exact to the audit-off single
        worker with the audit fully on — instrumentation cannot perturb
        the merge/carry/replay machinery."""
        vals = _vals()
        sink1, sink2 = ListSink(), ListSink()
        _run_worker(vals, sink1, audit="off")
        mesh = InProcessMesh(
            _make_bus(), "flows", 4,
            model_factory=lambda: _build_models(vals),
            config=WorkerConfig(poll_max=BATCH, snapshot_every=0,
                                obs_audit="full"),
            sinks=[sink2], submit_every=2)
        mesh.start()
        victim = mesh.members[1]
        deadline = time.time() + 120
        while time.time() < deadline:
            w = victim.worker
            if w is not None and w.flows_seen >= BATCH:
                break
            time.sleep(0.002)
        else:
            pytest.fail("victim never processed a batch")
        mesh.kill_member(1)
        mesh.wait_idle()
        mesh.finalize()
        top1 = sink1.tables["top_talkers"][0]
        top2 = sink2.tables["top_talkers"][0]
        v1, v2 = np.asarray(top1["valid"]), np.asarray(top2["valid"])
        assert int(v1.sum()) == int(v2.sum())
        for col in TOP_COLS:
            a = np.asarray(top1[col])[v1]
            b = np.asarray(top2[col])[v2]
            assert (a == b).all(), col


# ---------------------------------------------------------------------------
# mesh-merged audit counters == single-worker oracle cohort
# ---------------------------------------------------------------------------


class TestMeshAuditMerge:
    def test_merged_cohort_bit_equals_oracle(self):
        """Per-member audit partials ride the submission envelope and
        fold at the coordinator as u64 sums; the merged cohort must
        bit-equal what a single worker seeing the whole stream sampled
        (same deterministic key sample, same totals)."""
        vals = _vals("-sketch.backend", "host")
        # oracle: single worker, audit in capture mode so partials are
        # retained instead of evaluated-and-dropped
        oracle_parts: dict[int, dict] = {}
        worker = StreamWorker(
            Consumer(_make_bus(), "flows", fixedlen=True),
            _build_models(vals), [ListSink()],
            WorkerConfig(poll_max=BATCH, snapshot_every=0,
                         sketch_backend="host", obs_audit="full"))
        worker.fused.audit.capture = \
            lambda name, slot, part: oracle_parts.setdefault(
                slot, {}).setdefault(name, part)
        worker.run(stop_when_idle=True)
        assert oracle_parts, "oracle closed no audited windows"
        mesh = InProcessMesh(
            _make_bus(), "flows", 2,
            model_factory=lambda: _build_models(vals),
            config=WorkerConfig(poll_max=BATCH, snapshot_every=0,
                                sketch_backend="host",
                                obs_audit="full"),
            sinks=[ListSink()])
        mesh.run()
        coord = mesh.coordinator
        checked = 0
        for slot, models in oracle_parts.items():
            for name, part in models.items():
                merged = coord.audit_cohort(name, slot)
                assert merged is not None, (name, slot)
                assert merged["keys"].dtype == np.uint32
                assert merged["vals"].dtype == np.uint64
                assert (merged["keys"] == part["keys"]).all(), (name, slot)
                assert (merged["vals"] == part["vals"]).all(), (name, slot)
                checked += 1
        assert checked >= 1
        # and the coordinator published the network-wide report
        reports = coord.audit_reports()
        assert "top_talkers" in reports
        assert reports["top_talkers"]["sampled_keys"] > 0


# ---------------------------------------------------------------------------
# coordinator protocol: merged-audit publish + series lifecycle
# ---------------------------------------------------------------------------


class TestCoordinatorAudit:
    @staticmethod
    def _hh_contrib(slot, audit_vals, member_seed, ranges, wm,
                    final=False):
        from flow_pipeline_tpu.mesh import codec

        cfg = HeavyHitterConfig(key_cols=("src_as", "dst_as"),
                                value_cols=("bytes", "packets"),
                                width=256, capacity=8, batch_size=64,
                                scale_col=None)
        keys = np.arange(4, dtype=np.uint32).reshape(2, 2)
        tkeys = np.full((8, 2), 0xFFFFFFFF, np.uint32)
        tvals = np.zeros((8, 3), np.float32)
        tkeys[:2] = keys
        tvals[:2] = np.asarray(audit_vals, np.float32)
        payload = {
            "kind": "hh",
            "cms": np.zeros((3, cfg.depth, cfg.width), np.uint64),
            "table_keys": tkeys, "table_vals": tvals,
            "audit": {"keys": keys,
                      "vals": np.asarray(audit_vals, np.uint64),
                      "scale": 1, "evictions": 1},
        }
        return cfg, codec.encode({
            "member": f"m{member_seed}", "ranges": ranges,
            "watermark": wm, "closed": {slot: {"hh": payload}},
            "open": {}, "flows": 10, "final": final, "release": False,
            "span": {"sub": member_seed, "member": f"m{member_seed}",
                     "sent": time.time(), "chunk": 1, "windows": [slot]},
        })

    def test_merged_audit_is_u64_sum_and_member_series_removed(self):
        from flow_pipeline_tpu.mesh import ModelSpec, MeshCoordinator

        cfg, blob_a = self._hh_contrib(
            300, [[100, 10, 5], [50, 5, 2]], 1, {0: [0, 5]}, 900,
            final=True)
        spec = ModelSpec("hh", "hh", cfg, k=8, window_seconds=300)
        c = MeshCoordinator([spec], 2, heartbeat_timeout=1e9)
        c.join("a"), c.join("b")
        sa, sb = c.sync("a"), c.sync("b")
        pa = list(sa["assign"])[0]
        pb = list(sb["assign"])[0]
        _, blob_a = self._hh_contrib(
            300, [[100, 10, 5], [50, 5, 2]], 1, {pa: [0, 5]}, 900,
            final=True)
        _, blob_b = self._hh_contrib(
            300, [[30, 3, 1], [20, 2, 1]], 2, {pb: [0, 5]}, 900,
            final=True)
        assert c.submit("a", blob_a)["ok"]
        assert c.submit("b", blob_b)["ok"]
        merged = c.audit_cohort("hh", 300)
        assert merged is not None
        assert (merged["vals"] == np.array(
            [[130, 13, 6], [70, 7, 3]], np.uint64)).all()
        assert merged["evictions"] == 2
        rep = c.audit_reports()["hh"]
        assert rep["sampled_keys"] == 2
        # submit->merge latency is member-labeled now; fencing removes
        # the member's histogram series (Histogram.remove regression)
        assert 'member="a"' in c._m["sub2merge_s"].render()
        c.fence("a")
        assert 'member="a"' not in c._m["sub2merge_s"].render()
        assert 'member="b"' in c._m["sub2merge_s"].render()


class TestHistogramRemove:
    def test_remove_drops_one_label_set(self):
        from flow_pipeline_tpu.obs.metrics import Histogram

        h = Histogram("t_hist_remove", "t", buckets=(1.0, 2.0))
        h.observe(0.5, member="a")
        h.observe(1.5, member="b")
        assert 'member="a"' in h.render()
        h.remove(member="a")
        text = h.render()
        assert 'member="a"' not in text
        assert 'member="b"' in text
        assert h.value(member="a") == (0, 0.0)
        assert h.value(member="b") == (1, 1.5)

    def test_remove_missing_label_set_is_noop(self):
        from flow_pipeline_tpu.obs.metrics import Histogram

        h = Histogram("t_hist_remove2", "t", buckets=(1.0,))
        h.remove(member="ghost")  # must not raise
        h.observe(0.5)
        assert h.value() == (1, 0.5)


# ---------------------------------------------------------------------------
# flowserve: /query/audit
# ---------------------------------------------------------------------------


class TestServeAudit:
    def test_query_audit_serves_last_reports(self):
        from flow_pipeline_tpu.serve import ServeServer, attach_worker

        vals = _vals("-sketch.backend", "host")
        worker = StreamWorker(
            Consumer(_make_bus(n_flows=8192), "flows", fixedlen=True),
            _build_models(vals), [ListSink()],
            WorkerConfig(poll_max=BATCH, snapshot_every=0,
                         sketch_backend="host", obs_audit="full"))
        pub = attach_worker(worker, refresh=0.0)
        worker.run(stop_when_idle=True)
        snap = pub.store.current
        assert snap is not None and snap.audit, \
            "publish carried no audit reports"
        # start() before stop(): BaseServer.shutdown() waits on the
        # serve_forever loop having run at least once
        server = ServeServer(pub.store, port=0).start()
        try:
            resp = server._respond("/query/audit", None)
            head, _, body = resp.partition(b"\r\n\r\n")
            assert b"200" in head.split(b"\r\n")[0]
            doc = json.loads(body)
            assert doc["models"]
            name, rep = next(iter(doc["models"].items()))
            assert "cms_err" in rep and "fill_ratio" in rep
            # unknown model answers 400, not a dropped connection
            resp = server._respond("/query/audit?model=nope", None)
            assert resp.startswith(b"HTTP/1.1 400")
            # responses are counted by code for the 5xx alert
            assert pub.store.m_responses.value(code="200") >= 1
            assert pub.store.m_responses.value(code="400") >= 1
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# flags / plumbing
# ---------------------------------------------------------------------------


def test_obs_audit_flag_registered_and_threaded():
    assert "obs.audit" in KNOWN_FLAGS
    vals = _vals("-obs.audit", "full")
    from flow_pipeline_tpu.cli import _worker_config

    assert _worker_config(vals).obs_audit == "full"
    with pytest.raises(ValueError):
        StreamWorker(None, {}, [], WorkerConfig(obs_audit="bogus"))


def test_audit_metrics_registered_eagerly_on_worker():
    from flow_pipeline_tpu.obs import REGISTRY

    StreamWorker(None, {}, [], WorkerConfig())
    for name in ("sketch_estimate_error_ratio", "sketch_cms_fill_ratio",
                 "sketch_table_occupancy", "sketch_hh_recall",
                 "sketch_audit_false_drop_total"):
        assert name in REGISTRY._metrics, name
